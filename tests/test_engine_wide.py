"""Wide-round equivalence: kv_step_scan_wide over scheduled planes is
bit-identical to kv_step_scan over the same ops in (group, lane)
order, and the scheduler's plans are well-formed (per-slot order
preserved, lanes conflict-free) — the correctness contract of
SURVEY §2.7's "conflict-free slots advance in one batched kernel
step".
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.ops import schedule as sched  # noqa: E402

KINDS = np.array([eng.OP_NOOP, eng.OP_GET, eng.OP_PUT, eng.OP_CAS])


def _random_planes(rng, k, e, n_slots, p_noop=0.2, p_dup=0.5,
                   p_invalid=0.05):
    """Random mixed-op [K, E] planes with engineered slot duplicates
    (the scheduler's whole reason to exist)."""
    kind = rng.choice(KINDS, (k, e), p=[p_noop, 0.35, 0.35, 0.1])
    slot = rng.integers(0, n_slots, (k, e), dtype=np.int32)
    # Force duplicate chains: some rows reuse the previous row's slot.
    for i in range(1, k):
        reuse = rng.random(e) < p_dup
        slot[i, reuse] = slot[i - 1, reuse]
    slot[rng.random((k, e)) < p_invalid] = -1
    val = rng.integers(1, 1 << 20, (k, e), dtype=np.int32)
    lease = rng.random((k, e)) < 0.5
    # CAS expectations: mostly misses, some (0, 0) create-if-missing.
    xe = rng.integers(0, 3, (k, e), dtype=np.int32)
    xs = rng.integers(0, 3, (k, e), dtype=np.int32)
    return kind.astype(np.int32), slot, val, lease, xe, xs


def _scalar_oracle(state, planes, up):
    """Apply the plan's serialization through the scalar scan."""
    kind, slot, val, lease, xe, xs = planes
    plan = sched.schedule_wide(kind, slot, val, lease, xe, xs)
    ok, _ = sched.flat_order(plan)
    ee = np.arange(kind.shape[1])[None, :]
    reorder = lambda p: jnp.asarray(p[ok, ee])  # noqa: E731
    st, res = eng.kv_step_scan(
        state, reorder(kind), reorder(slot), reorder(val),
        reorder(lease), up, exp_epoch=reorder(xe), exp_seq=reorder(xs))
    return st, res, plan, ok


def _elected_state(rng, e, m, s):
    state = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(
        state, jnp.ones((e,), bool), jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())
    return state, up


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_wide_equals_sequential(seed):
    rng = np.random.default_rng(seed)
    e, m, s, k = 17, 3, 32, 12
    state, up = _elected_state(rng, e, m, s)

    planes = _random_planes(rng, k, e, s)
    st_seq, res_seq, plan, ok = _scalar_oracle(state, planes, up)

    st_w, res_w = eng.kv_step_scan_wide(
        state, jnp.asarray(plan.kind), jnp.asarray(plan.slot),
        jnp.asarray(plan.val), jnp.asarray(plan.lease_ok), up,
        exp_epoch=jnp.asarray(plan.exp_epoch),
        exp_seq=jnp.asarray(plan.exp_seq))

    # Final state bit-equal.
    for name, a, b in zip(st_seq._fields, st_seq, st_w):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"state field {name}")

    # Per-op results: route the wide [G, E, W] results back to original
    # (k, e) order and compare against the sequential results (which
    # ran in plan order — invert that reorder).
    ee = np.arange(e)[None, :]
    inv = np.empty_like(ok)
    inv[ok, ee] = np.arange(k)[:, None] * np.ones((1, e), np.int32)
    active = planes[0] != eng.OP_NOOP  # NOOP padding routes to (0, 0):
    #                                    its routed result is undefined
    for field in ("committed", "get_ok", "found", "value", "obj_vsn"):
        wide = sched.route_results(plan, np.asarray(getattr(res_w, field)))
        seq = np.asarray(getattr(res_seq, field))[inv, ee]
        np.testing.assert_array_equal(wide[active], seq[active],
                                      err_msg=field)


def test_wide_with_down_peers_and_duplicates():
    """Quorum edges (down peers) and all-duplicate columns (degenerate
    W=1 chains) under the wide path."""
    rng = np.random.default_rng(7)
    e, m, s, k = 9, 5, 16, 8
    state, up = _elected_state(rng, e, m, s)
    up = np.array(up)
    up[::3, m - 2:] = False  # minority down in every 3rd ensemble
    up = jnp.asarray(up)

    kind, slot, val, lease, xe, xs = _random_planes(rng, k, e, s)
    slot[:, 0] = 5  # one column: every op on the same slot
    planes = (kind, slot, val, lease, xe, xs)
    st_seq, res_seq, plan, ok = _scalar_oracle(state, planes, up)
    st_w, res_w = eng.kv_step_scan_wide(
        state, jnp.asarray(plan.kind), jnp.asarray(plan.slot),
        jnp.asarray(plan.val), jnp.asarray(plan.lease_ok), up,
        exp_epoch=jnp.asarray(plan.exp_epoch),
        exp_seq=jnp.asarray(plan.exp_seq))
    for name, a, b in zip(st_seq._fields, st_seq, st_w):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"state field {name}")
    # The all-duplicates column serialized into k groups.
    assert plan.map_g[:, 0].max() >= (np.asarray(kind)[:, 0]
                                      != eng.OP_NOOP).sum() - 1


def test_schedule_preserves_per_slot_order():
    rng = np.random.default_rng(3)
    k, e, s = 20, 5, 8
    kind, slot, val, lease, xe, xs = _random_planes(rng, k, e, s,
                                                    p_dup=0.7)
    plan = sched.schedule_wide(kind, slot, val, lease, xe, xs)
    active = kind != eng.OP_NOOP
    for col in range(e):
        for sl in np.unique(slot[:, col]):
            if sl < 0:
                continue
            ops = np.where(active[:, col] & (slot[:, col] == sl))[0]
            groups = plan.map_g[ops, col]
            # same-slot ops occupy strictly increasing groups (k order)
            assert (np.diff(groups) > 0).all()
    # Within a (group, ensemble): valid slots distinct.
    g, w = plan.kind.shape[0], plan.kind.shape[2]
    for gi in range(g):
        for col in range(e):
            sls = plan.slot[gi, col][plan.kind[gi, col] != eng.OP_NOOP]
            sls = sls[sls >= 0]
            assert len(set(sls.tolist())) == len(sls)


def test_schedule_width_cap_degenerates_to_sequential():
    rng = np.random.default_rng(11)
    kind, slot, val, lease, xe, xs = _random_planes(rng, 6, 4, 64,
                                                    p_dup=0.0)
    plan = sched.schedule_wide(kind, slot, val, lease, xe, xs,
                               max_width=2)
    assert plan.kind.shape[2] == 1 and plan.kind.shape[0] >= 6


def test_wide_sharded_matches_local():
    """ShardedEngine.full_step_wide over the virtual 8-device mesh is
    bit-equal to the local kernel — the wide path's ICI collectives
    (psum/pmax over the 'peer' axis) preserve the exact semantics."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    from riak_ensemble_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.make_mesh(4, 2)
    se = mesh_mod.ShardedEngine(mesh)
    e, m, s = 8, 4, 16
    g, w = 2, 4
    rng = np.random.default_rng(13)

    st_local, up = _elected_state(rng, e, m, s)

    kind = jnp.asarray(rng.choice(
        [eng.OP_NOOP, eng.OP_GET, eng.OP_PUT], (g, e, w)), jnp.int32)
    # distinct valid slots per (group, ensemble) row
    slot = jnp.asarray(np.stack(
        [np.stack([rng.permutation(s)[:w] for _ in range(e)])
         for _ in range(g)]).astype(np.int32))
    val = jnp.asarray(rng.integers(1, 99, (g, e, w)), jnp.int32)
    lease = jnp.asarray(rng.random((g, e, w)) < 0.5)
    # Re-elect half the ensembles in the same fused step so the won
    # output (P('ens') spec) and the election's peer-axis collectives
    # are part of the bit-equality check, not dead outputs.
    elect = jnp.asarray(np.arange(e) % 2 == 0)
    cand = jnp.ones((e,), jnp.int32)

    st_a, won_a, res_a = eng.full_step_wide(
        st_local, elect, cand, kind, slot, val, lease, up)

    st_sh = se.shard_state(st_local)
    st_b, won_b, res_b = se.full_step_wide(
        st_sh, elect, cand, kind, slot, val, lease, up)

    np.testing.assert_array_equal(np.asarray(won_a), np.asarray(won_b))
    assert bool(np.asarray(won_a)[::2].all())  # elections really ran
    for name, a, b in zip(st_a._fields, st_a, st_b):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"state field {name}")
    for name, a, b in zip(res_a._fields, res_a, res_b):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"result field {name}")
