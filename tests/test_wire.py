"""Restricted wire codec: roundtrips, allowlist rejection, hostile
frames.  The codec replaces pickle on the TCP transport so a peer that
can reach the node port can inject at worst a protocol message, never
code (disterl's property, ADVICE r1)."""

import pytest

from riak_ensemble_tpu import wire
from riak_ensemble_tpu.state import ClusterState
from riak_ensemble_tpu.types import (EnsembleInfo, Fact, NOTFOUND, Obj,
                                     PeerId)


CASES = [
    None, True, False, 0, 1, -1, 2 ** 80, -(2 ** 80), 1.5, -0.0,
    "", "node0", "ünïcode", b"", b"\x00\xffpayload",
    (), (1, 2), [1, [2, [3]]], {"a": 1, 2: (3,)}, {1, 2}, frozenset({3}),
    NOTFOUND,
    PeerId(1, "node0"), PeerId("root", "node1"),
    Obj(epoch=3, seq=7, key="k", value=b"v"),
    Obj(epoch=1, seq=1, key=("composite", 2), value=NOTFOUND),
    Fact(epoch=2, seq=5, leader=PeerId(0, "n0"),
         views=((PeerId(0, "n0"), PeerId(1, "n1")),),
         view_vsn=(1, 0), pend_vsn=None, commit_vsn=(0, 0),
         pending=((2, 1), ((PeerId(1, "n1"),),))),
    EnsembleInfo(vsn=(1, 2), leader=None, views=((PeerId(0, "n0"),),),
                 seq=None),
    ClusterState(id=("node0", 123.5), enabled=True, members_vsn=(1, 0),
                 members=frozenset({"node0", "node1"}),
                 ensembles={"root": EnsembleInfo(
                     vsn=(0, 1), leader=PeerId("root", "node0"),
                     views=((PeerId("root", "node0"),),), seq=(1, 1))},
                 pending={"root": ((1, 1), ((PeerId(2, "node2"),),))}),
]


@pytest.mark.parametrize("value", CASES, ids=lambda v: repr(v)[:40])
def test_roundtrip(value):
    out = wire.decode(wire.encode(value))
    assert out == value
    assert type(out) is type(value)


def test_notfound_stays_singleton():
    assert wire.decode(wire.encode(NOTFOUND)) is NOTFOUND


def test_nested_message_shape():
    # a realistic wire frame: (dst, msg) with a reply-from tuple
    frame = (("peer", "kv", PeerId(1, "node1")),
             ("get", "k", (("collector", "node0", 42), 7), 3))
    assert wire.decode(wire.encode(frame)) == frame


def test_rejects_unencodable():
    class Evil:
        pass
    with pytest.raises(wire.WireError):
        wire.encode(Evil())
    with pytest.raises(wire.WireError):
        wire.encode(lambda: None)  # closures never cross the wire


def test_rejects_unknown_tag():
    with pytest.raises(wire.WireError):
        wire.decode(b"Q")


def test_rejects_truncated():
    payload = wire.encode((1, "abc", b"xyz"))
    for cut in range(len(payload)):
        with pytest.raises(wire.WireError):
            wire.decode(payload[:cut])


def test_rejects_trailing_garbage():
    with pytest.raises(wire.WireError):
        wire.decode(wire.encode(1) + b"N")


def test_rejects_unknown_record_code():
    with pytest.raises(wire.WireError):
        wire.decode(b"R\x7f")


def test_rejects_deep_nesting_bomb():
    payload = b"t\x01" * 64 + b"N"
    with pytest.raises(wire.WireError):
        wire.decode(payload)


def test_rejects_oversized_count():
    # claims 2^40 tuple elements with no bodies: must fail cleanly,
    # not allocate
    payload = b"t" + bytes([0x80, 0x80, 0x80, 0x80, 0x80, 0x01])
    with pytest.raises(wire.WireError):
        wire.decode(payload)


def test_no_pickle_in_transport():
    import riak_ensemble_tpu.netruntime as nrt
    import inspect
    assert "pickle" not in inspect.getsource(nrt)


def test_funref_roundtrip_and_resolve():
    """Modify callbacks cross the wire as ("fn", name, bound) data —
    the MFA analog (root.erl:82,104) — and resolve by registry."""
    from riak_ensemble_tpu import funref
    import riak_ensemble_tpu.root  # noqa: F401  (registers root:*)

    spec = funref.ref("root:join", "node9")
    got = wire.decode(wire.encode(spec))
    assert got == spec
    fn = funref.resolve(got)
    from riak_ensemble_tpu import state as statelib
    cs = statelib.new_state(("c", 1.0))
    out = fn((1, 0), cs)
    assert "node9" in out.members


def test_funref_rejects_unregistered():
    from riak_ensemble_tpu import funref
    with pytest.raises(ValueError):
        funref.resolve(("fn", "no:such", ()))
    with pytest.raises(ValueError):
        funref.resolve("not-a-spec")


def test_encode_rejects_nesting_bomb():
    """Pathological user values must become WireError (dropped frame),
    not RecursionError (dead sender task)."""
    v = []
    for _ in range(1000):
        v = [v]
    with pytest.raises(wire.WireError):
        wire.encode(v)


def test_encode_rejects_self_reference():
    v = []
    v.append(v)
    with pytest.raises(wire.WireError):
        wire.encode(v)


def test_decode_malformed_raises_wireerror_only():
    """The documented contract: anything malformed raises WireError —
    not UnicodeDecodeError / TypeError — so callers can catch narrowly."""
    bad = [
        b"s\x01\xff",          # invalid utf-8 in str
        b"e\x01l\x00",         # set containing a list (unhashable)
        b"z\x01l\x00",         # frozenset containing a list
        b"d\x01l\x00N",        # dict with unhashable key
    ]
    for payload in bad:
        with pytest.raises(wire.WireError):
            wire.decode(payload)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_roundtrip_random_structures(seed):
    """Seeded structural fuzz: random nested allowlisted values must
    round-trip exactly (type-preserving)."""
    import numpy as _np

    rng = _np.random.default_rng(seed)

    def gen(depth=0):
        choices = 10 if depth < 4 else 6  # leaves only when deep
        c = int(rng.integers(choices))
        if c == 0:
            return None
        if c == 1:
            return bool(rng.integers(2))
        if c == 2:
            return int(rng.integers(-2**40, 2**40))
        if c == 3:
            return float(rng.normal())
        if c == 4:
            return bytes(rng.integers(0, 256, int(rng.integers(0, 12)),
                                      dtype=_np.uint8))
        if c == 5:
            return "".join(chr(int(rng.integers(32, 1000)))
                           for _ in range(int(rng.integers(0, 8))))
        n = int(rng.integers(0, 4))
        if c == 6:
            return tuple(gen(depth + 1) for _ in range(n))
        if c == 7:
            return [gen(depth + 1) for _ in range(n)]
        if c == 8:
            return {int(rng.integers(100)): gen(depth + 1)
                    for _ in range(n)}
        return PeerId(int(rng.integers(10)), f"n{int(rng.integers(4))}")

    for _ in range(200):
        v = gen()
        out = wire.decode(wire.encode(v))
        assert out == v and type(out) is type(v)


# -- native codec (native/wirecodec.cc) differential tests ---------------

def _native():
    mod = wire._native_codec()
    if mod is None:
        pytest.skip("native wire codec unavailable (no toolchain)")
    return mod


@pytest.mark.parametrize("seed", range(4))
def test_native_encode_byte_exact_with_python(seed):
    """Native and Python frames must be interchangeable on the wire:
    identical bytes for identical values (same tags, varints, int
    widths, container order)."""
    import numpy as _np

    mod = _native()
    rng = _np.random.default_rng(seed)

    def gen(depth=0):
        choices = 11 if depth < 4 else 6
        c = int(rng.integers(choices))
        if c == 0:
            return None
        if c == 1:
            return bool(rng.integers(2))
        if c == 2:
            # spans the small-int fast path, the 8-byte boundary, and
            # the arbitrary-precision slow path
            return int(rng.integers(-2**40, 2**40)) << int(rng.integers(40))
        if c == 3:
            return float(rng.normal())
        if c == 4:
            return bytes(rng.integers(0, 256, int(rng.integers(0, 12)),
                                      dtype=_np.uint8))
        if c == 5:
            return "".join(chr(int(rng.integers(32, 1000)))
                           for _ in range(int(rng.integers(0, 8))))
        n = int(rng.integers(0, 4))
        if c == 6:
            return tuple(gen(depth + 1) for _ in range(n))
        if c == 7:
            return [gen(depth + 1) for _ in range(n)]
        if c == 8:
            return {int(rng.integers(100)): gen(depth + 1)
                    for _ in range(n)}
        if c == 9:
            return frozenset(int(rng.integers(1000)) for _ in range(n))
        return PeerId(int(rng.integers(10)), f"n{int(rng.integers(4))}")

    for _ in range(300):
        v = gen()
        py = wire.encode_py(v)
        assert mod.encode(v) == py, v
        got = mod.decode(py)
        assert got == v and type(got) is type(v)


def test_native_int_edges_byte_exact():
    mod = _native()
    edges = [0, 1, -1, 127, 128, 129, -127, -128, -129, 255, 256,
             2**31 - 1, 2**31, -2**31, -2**31 - 1, 2**62, 2**63 - 1,
             2**63, -2**63, -2**63 - 1, 2**64, 2**200, -2**200]
    for v in edges:
        assert mod.encode(v) == wire.encode_py(v), v
        assert mod.decode(wire.encode_py(v)) == v, v


@pytest.mark.parametrize("seed", range(3))
def test_native_decode_error_parity_on_random_bytes(seed):
    """Hostile-input agreement: for random byte soup both decoders
    either produce the same value or both raise WireError (and the
    native one never raises anything else, segfaults excepted by
    construction)."""
    import numpy as _np

    mod = _native()
    rng = _np.random.default_rng(1000 + seed)
    for _ in range(2000):
        blob = bytes(rng.integers(0, 256, int(rng.integers(1, 40)),
                                  dtype=_np.uint8))
        try:
            a = ("ok", wire.decode_py(blob))
        except wire.WireError:
            a = ("err",)
        try:
            b = ("ok", mod.decode(blob))
        except wire.WireError:
            b = ("err",)
        if a[0] == b[0] == "ok":
            assert wire.encode_py(a[1]) == wire.encode_py(b[1]), \
                (blob.hex(), a, b)
        else:
            assert a[0] == b[0], (blob.hex(), a, b)


def test_native_mutated_valid_frames_error_parity():
    """Mutations of VALID frames (bit flips, truncation, extension)
    hit deeper decode paths than raw byte soup."""
    import numpy as _np

    mod = _native()
    rng = _np.random.default_rng(4242)
    base = wire.encode_py(
        {"k": (PeerId(1, "n1"), [1.5, NOTFOUND, -2**70, "déjà"],
               frozenset({1, 2}), b"\x00\xff")})
    for _ in range(3000):
        blob = bytearray(base)
        for _m in range(int(rng.integers(1, 4))):
            op = int(rng.integers(3))
            if op == 0 and blob:
                blob[int(rng.integers(len(blob)))] ^= \
                    1 << int(rng.integers(8))
            elif op == 1 and len(blob) > 1:
                del blob[int(rng.integers(len(blob))):]
            else:
                blob.extend(rng.integers(0, 256, 2, dtype=_np.uint8))
        blob = bytes(blob)
        try:
            a = ("ok", wire.decode_py(blob))
        except wire.WireError:
            a = ("err",)
        try:
            b = ("ok", mod.decode(blob))
        except wire.WireError:
            b = ("err",)
        # NaN-safe equivalence: compare canonical re-encodings (two
        # separately built NaNs are != even inside equal structures)
        if a[0] == b[0] == "ok":
            assert wire.encode_py(a[1]) == wire.encode_py(b[1]), \
                (blob.hex(), a, b)
        else:
            assert a[0] == b[0], (blob.hex(), a, b)


def test_native_depth_limits_match():
    mod = _native()
    v = []
    for _ in range(1000):
        v = [v]
    with pytest.raises(wire.WireError):
        mod.encode(v)
    deep = b"l\x01" * 40 + b"N"
    for dec in (wire.decode_py, mod.decode):
        with pytest.raises(wire.WireError):
            dec(deep)
