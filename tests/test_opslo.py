"""Per-op SLO tracing (docs/ARCHITECTURE.md §11, round 9).

Covers the tentpole contracts end to end: stamp monotonicity and the
op→flush_id join on the pipelined (depth 2) keyed path, the join
surviving a batch split across flushes, ack-after-quorum on a LIVE
replication group, the injected-slow-op demo (client-perceived tail
attributed to its dominating stage via ``obs.timeline``), and the
compile-event hook catching a deliberately un-warmed (K, A) bucket.
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import obs  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.obs import opslo  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)


def _acked_rows(ring):
    return [r for r in range(ring.cap) if ring.t_ack[r] > 0.0]


def test_op_spans_depth2_pipelined():
    """Every keyed op on a depth-2 pipelined service gets the five
    monotone stamps and a flush_id that joins a recorded leader
    timeline; the per-kind histogram counts every op exactly once."""
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 8, tick=None,
                                 max_ops_per_tick=4,
                                 pipeline_depth=2)
    futs = []
    for rnd in range(3):
        for e in range(4):
            futs.append(svc.kput_many(
                e, [f"k{rnd}a", f"k{rnd}b"], [b"1", b"2"]))
        while any(svc.queues):
            svc.flush()
    assert all(f.done for f in futs)
    ring = svc._slo
    rows = _acked_rows(ring)
    assert rows, "no acked ring rows recorded"
    for r in rows:
        assert ring.t_submit[r] <= ring.t_enq[r] <= ring.t_join[r] \
            <= ring.t_settle[r] <= ring.t_ack[r], \
            ring.row_view(r)
        assert ring.fid[r] > 0, "acked op without a flush_id join"
        # the joined flush has a leader span record under the SAME id
        tl = obs.timeline(int(ring.fid[r]))
        assert tl is not None and "leader" in tl
    # per-kind histogram: every put counted once (3 rounds x 4 ens x
    # 2 keys), client-perceived latency nonzero
    put = svc._h_op.labels("put")
    assert put.count == 24
    assert put.percentile(0.99) >= put.percentile(0.5) >= 0
    # reads join too, including the kind split
    f = svc.kget_many(0, ["k0a"])
    # leased fast read: no flush — lands as get_fast
    assert f.done
    assert svc._h_op.labels("get_fast").count >= 1
    svc.stop()


def test_op_flush_join_survives_batch_split():
    """A kput_many wider than the flush's K cap splits: the head
    settles with flush N, the tail re-enters the ring and settles
    with flush N+1 — two rows, two DIFFERENT flush_ids, op counts
    conserved."""
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=2)
    ring = svc._slo
    first_row = ring._next
    fut = svc.kput_many(0, ["a", "b", "c", "d"],
                        [b"1", b"2", b"3", b"4"])
    while not fut.done:
        svc.flush()
    assert [r[0] for r in fut.value] == ["ok"] * 4
    rows = [r for r in range(first_row, ring._next)
            if ring.kind[r & ring.mask] != 0]
    acked = [r & ring.mask for r in rows
             if ring.t_ack[r & ring.mask] > 0.0]
    assert len(acked) == 2, "split batch must occupy two ring rows"
    fids = {int(ring.fid[r]) for r in acked}
    assert len(fids) == 2, f"head and tail joined the same flush: {fids}"
    assert sum(int(ring.n[r]) for r in acked) == 4, \
        "op weight not conserved across the split"
    # both halves' flushes are queryable timelines (a structured
    # miss — the store's not-found shape since round 13 — would mean
    # the join broke)
    for fid in fids:
        tl = obs.timeline(fid)
        assert tl and not tl.get("miss"), tl
    svc.stop()


def test_op_ack_lands_after_quorum_settle(tmp_path):
    """Replication-group mode: client futures resolve only at the
    host-quorum settle, and the ring's ack stamps land at (or after)
    that settle — never at the device resolve that precedes it."""
    from riak_ensemble_tpu.parallel import repgroup

    servers = [repgroup.ReplicaServer(4, 3, 8,
                                      data_dir=str(tmp_path / f"r{i}"),
                                      config=fast_test_config())
               for i in (1, 2)]
    svc = repgroup.ReplicatedService(
        WallRuntime(), 4, 1, 8, group_size=3,
        peers=[("127.0.0.1", s.repl_port) for s in servers],
        ack_timeout=30.0, max_ops_per_tick=4,
        config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover()
    settle_t: list = []
    orig_settle = svc._settle_batch

    def tracked_settle(batch):
        settle_t.append(time.perf_counter())
        return orig_settle(batch)

    svc._settle_batch = tracked_settle
    ring = svc._slo
    first_row = ring._next
    futs = [svc.kput_many(e, ["a", "b"], [b"1", b"2"])
            for e in range(4)]
    while any(svc.queues):
        svc.flush()
    svc._drain_pending(block_all=True)
    assert all(f.done for f in futs)
    assert settle_t, "no quorum settle observed"
    rows = [r & ring.mask for r in range(first_row, ring._next)]
    acked = [r for r in rows if ring.t_ack[r] > 0.0]
    assert acked, "no acked ring rows on the replicated leader"
    for r in acked:
        assert ring.t_join[r] <= ring.t_settle[r] <= ring.t_ack[r]
        # the ack stamp postdates the FIRST quorum settle — the
        # device resolve ran earlier, but no op acked before a
        # host-quorum decision existed
        assert ring.t_ack[r] >= settle_t[0], \
            (ring.row_view(r), settle_t)
        tl = obs.timeline(int(ring.fid[r]))
        assert tl is not None and "leader" in tl
    # the health verb's group section reflects the live quorum plane
    h = svc.health()
    assert h["schema"] == "retpu-health-v1"
    grp = h["group"]
    assert grp["leader"] is True and grp["size"] == 3
    assert grp["peers_connected"] == 2
    assert grp["pipeline_pending"] == 0
    assert h["ensembles_with_leader"] == 4
    svc.stop()
    for s in servers:
        s.stop()


def test_injected_slow_op_tail_attribution(monkeypatch):
    """Acceptance demo: one injected-slow op's client-perceived tail
    is correctly attributed via ``obs.timeline`` — a queue-stalled op
    shows ``queue_wait`` dominating its stage split, a d2h-stalled op
    shows the flush stage dominating WITH the flush's own dominant
    mark naming ``device_d2h``."""
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 8, tick=None,
                                 max_ops_per_tick=2)
    # steady state first (compiles out of the way)
    for i in range(4):
        f = svc.kput(i % 4, "w", b"x")
        while not f.done:
            svc.flush()

    # (1) queue-wait domination: enqueue, stall the flush driver
    fut = svc.kput_many(0, ["slow"], [b"v"])
    time.sleep(0.06)
    while not fut.done:
        svc.flush()
    ring = svc._slo
    # the stalled op is the newest settled entry (the warm-up ops'
    # first flush is slower still — it ate the first-use compile,
    # itself correctly attributed to its 'flush' stage)
    row = max(_acked_rows(ring), key=lambda r: ring.t_ack[r])
    fid = int(ring.fid[row])
    tl = obs.timeline(fid)
    slow = tl["leader"]["slow_ops"][0]
    assert slow["ms"] >= 55.0, slow
    st = slow["stages_ms"]
    assert st["queue_wait"] > max(st["flush"], st["ack"],
                                  st["assign"]), slow

    # (2) device/d2h domination: stall the packed-result fetch
    orig = svc._fetch_packed

    def slow_fetch(fl):
        time.sleep(0.08)
        return orig(fl)

    monkeypatch.setattr(svc, "_fetch_packed", slow_fetch)
    fut = svc.kput_many(1, ["slow2"], [b"v"])
    while not fut.done:
        svc.flush()
    monkeypatch.undo()
    rows2 = [r for r in _acked_rows(ring)
             if ring.kind[r] and ring.ens[r] == 1
             and ring.t_ack[r] - ring.t_submit[r] > 0.07]
    assert rows2, "stalled op not found in the ring"
    fid2 = int(ring.fid[rows2[-1]])
    slow2 = obs.timeline(fid2)["leader"]["slow_ops"][0]
    st2 = slow2["stages_ms"]
    assert st2["flush"] > max(st2["queue_wait"], st2["ack"],
                              st2["assign"]), slow2
    # the dominating PR 6 flush mark rides the tail sample: the
    # stall sat in the d2h wait
    assert slow2["flush_mark"] == "device_d2h", slow2
    svc.stop()


def test_compile_events_catch_unwarmed_bucket():
    """Acceptance: a deliberately un-warmed (K, A) pack bucket pays
    its first-use compile at SERVE time — and the compile-event hook
    names it (``retpu_compile_events_total{phase="serve"}``) instead
    of leaving a dispatch-p99 mystery.  E=24 is unique to this test
    (process-wide jit caches are shared), so the miss is
    deterministic."""
    svc = BatchedEnsembleService(WallRuntime(), 24, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    # warm ONLY the k=1 pack bucket: the step ladder always warms in
    # full, so the k=2 flush below hits a warmed step but an
    # un-warmed pack program
    svc.warmup(buckets=[(1, None)])
    assert svc._c_compile.labels("warmup").value > 0, \
        "warmup compiles must be counted under phase=warmup"
    serve0 = svc._c_compile.labels("serve").value
    fut = svc.kput_many(0, ["a", "b"], [b"1", b"2"])  # k bucket 2
    while not fut.done:
        svc.flush()
    served = svc._c_compile.labels("serve").value - serve0
    assert served >= 1, "un-warmed bucket compile not caught"
    ev = [e for e in svc._compile_log if e["phase"] == "serve"]
    assert ev, "serve-phase compile left no log entry"
    assert ev[-1]["fn"] == "pack", ev[-1]
    assert ev[-1]["compile_ms"] > 0
    # the un-warmed bucket's shape signature is recorded (K=2 rows)
    assert "[2," in ev[-1]["shapes"], ev[-1]
    # and the events ride the flight-dump extras section
    extras = svc._flight_extras()
    assert extras["compile_events"], extras
    assert any(e["phase"] == "serve" for e in extras["compile_events"])
    svc.stop()


def test_ring_bounded_and_obs_off_short_circuit(monkeypatch):
    """The ring is bounded (overwrites, never grows) and RETPU_OBS=0
    constructs no ring at all — zero stamp work on the hot path."""
    ring = opslo.OpSloRing(capacity=64)
    for i in range(200):
        t = float(i + 1)
        ring.record_flush([2], [0], [1], [0.0], [t], i + 1, t,
                          t + 1.0, t + 2.0)
    assert ring.cap == 64 and ring._next == 200
    monkeypatch.setenv("RETPU_OBS", "0")
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=2)
    assert svc._slo is None
    f = svc.kput(0, "k", b"v")
    while not f.done:
        svc.flush()
    assert f.value[0] == "ok"
    assert svc._h_op.count == 0 and not svc._h_op._children
    svc.stop()


def test_ring_capacity_knob(monkeypatch):
    monkeypatch.setenv("RETPU_SLO_RING", "100")
    assert opslo.ring_capacity() == 128
    monkeypatch.setenv("RETPU_SLO_RING", "junk")
    assert opslo.ring_capacity() == 4096
