"""Replica quorum across OS-process failure domains (VERDICT r3 #1).

The reference survives machine death because every commit's quorum
crosses node boundaries (riak_ensemble_msg.erl:132-142;
doc/Readme.md:49-63).  These tests drive the scale-path analog —
:mod:`riak_ensemble_tpu.parallel.repgroup` — with REAL kill -9 and
SIGSTOP against replica host processes:

- commits keep succeeding while a replica host is dead,
- zero acked writes are lost (read-back after failover sweeps), and
- a restarted host catches up (snapshot re-sync) and then carries a
  quorum on its own,
- a superseded leader is fenced (the sc.erl partition premise,
  test/sc.erl:1012-1036).
"""

import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.linearizability import (  # noqa: E402
    KeyModel, Violation)
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import WallRuntime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

N_ENS = 4
N_SLOTS = 8
GROUP = 3


def _free_port() -> int:
    with socket.create_server(("127.0.0.1", 0)) as s:
        return s.getsockname()[1]


def _spawn_replica(data_dir: str, repl_port: int = 0,
                   client_port: int = 0, extra=()):
    """One replica host process (CPU-pinned child; the sitecustomize
    TPU plugin would hang on the dead tunnel otherwise).  A RESTART
    must reuse its old ports — the leader's links keep dialing the
    address a host registered with, exactly like a rebooted machine
    keeping its hostname."""
    child = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from riak_ensemble_tpu.parallel import repgroup
        repgroup.main(["--n-ens", "{N_ENS}", "--group-size", "{GROUP}",
                       "--n-slots", "{N_SLOTS}", "--fast",
                       "--repl-port", "{repl_port}",
                       "--client-port", "{client_port}",
                       "--data-dir", {data_dir!r}] + {list(extra)!r})
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
    line = p.stdout.readline()
    assert line, p.stderr.read()[-3000:]
    parts = dict(kv.split("=") for kv in line.split()[2:])
    return p, int(parts["repl"]), int(parts["client"])


def _restart(procs, dirs, name):
    """Restart a dead replica on ITS OWN ports + data_dir."""
    _, repl, client = procs[name]
    procs[name] = _spawn_replica(dirs[name], repl_port=repl,
                                 client_port=client)
    return procs[name]


def _make_leader(tmp_path, repl_ports, ack_timeout=15.0):
    svc = repgroup.ReplicatedService(
        WallRuntime(), N_ENS, 1, N_SLOTS, group_size=GROUP,
        peers=[("127.0.0.1", p) for p in repl_ports],
        ack_timeout=ack_timeout, config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover(), "takeover needs a majority of replicas"
    return svc


def _settle(svc, futs, flushes=5):
    for _ in range(flushes):
        if all(f.done for f in futs):
            break
        svc.flush()
    assert all(f.done for f in futs)
    return [f.value for f in futs]


def _control(port: int, frame, timeout=120.0):
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        repgroup.send_frame(s, frame)
        return repgroup.recv_frame(s)


def _wait_synced(svc, n, deadline=60.0):
    """Heartbeat until n peers are connected AND re-synced (an idle
    leader drives liveness through empty applies)."""
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        svc.heartbeat()
        g = svc.stats()["group"]
        if g["peers_synced"] >= n:
            return
        time.sleep(0.1)
    raise AssertionError(f"peers never re-synced: {svc.stats()['group']}")


@pytest.fixture
def group(tmp_path):
    procs = {}
    dirs = {}
    for name in ("r1", "r2"):
        dirs[name] = str(tmp_path / name)
        procs[name] = _spawn_replica(dirs[name])
    svc = _make_leader(tmp_path, [procs["r1"][1], procs["r2"][1]])
    yield svc, procs, dirs, tmp_path
    svc.stop()
    for p, _, _ in procs.values():
        if p.poll() is None:
            p.kill()


def test_replica_kill9_commits_continue_and_restart_catches_up(group):
    """THE verdict r3 #1 criterion: (a) kill -9 one of three replica
    hosts mid-load and commits keep succeeding without it, (b) zero
    acked writes lost, (c) the restarted host catches up — proven by
    then killing the OTHER replica, so the restarted one must carry
    the quorum (and hold every acked write) itself."""
    svc, procs, dirs, tmp_path = group
    acked = {}

    def put_ok(phase, n=6):
        futs = []
        for i in range(n):
            e = i % N_ENS
            key = f"{phase}-{i}"
            futs.append((e, key, b"%s/%d" % (phase.encode(), i),
                         svc.kput(e, key, b"%s/%d" % (phase.encode(),
                                                      i))))
        _settle(svc, [f for *_, f in futs])
        for e, key, val, f in futs:
            assert f.value[0] == "ok", (phase, key, f.value)
            acked[(e, key)] = val

    put_ok("pre")

    # -- (a) kill -9 replica 1 mid-load: commits keep succeeding ------
    p1, p1_repl, _ = procs["r1"]
    p1.send_signal(signal.SIGKILL)
    p1.wait()
    put_ok("during")
    g = svc.stats()["group"]
    assert g["quorum_failures"] == 0, g

    # -- (c) restart replica 1 from its data_dir: leader re-syncs -----
    _restart(procs, dirs, "r1")
    _wait_synced(svc, 2)

    # -- now kill replica 2: the restarted host must carry the quorum
    p2, _, _ = procs["r2"]
    p2.send_signal(signal.SIGKILL)
    p2.wait()
    put_ok("after")

    # -- (b) zero acked writes lost: every acked key reads back -------
    futs = [(e, key, val, svc.kget(e, key))
            for (e, key), val in acked.items()]
    _settle(svc, [f for *_, f in futs])
    for e, key, val, f in futs:
        assert f.value == ("ok", val), \
            f"acked write lost at {(e, key)}: {f.value!r}"


def test_no_host_quorum_fails_ops_never_false_acks(group):
    """With both replicas dead the leader alone is a minority: every
    op must resolve 'failed' (never a false ack), and service resumes
    once a replica returns."""
    svc, procs, dirs, _ = group
    _settle(svc, [svc.kput(0, "k", b"v")])

    for name in ("r1", "r2"):
        p, _, _ = procs[name]
        p.send_signal(signal.SIGKILL)
        p.wait()

    futs = [svc.kput(0, "k2", b"x"), svc.kget(0, "k")]
    _settle(svc, futs)
    assert futs[0].value == "failed"
    assert futs[1].value == "failed"  # reads need the quorum too
    assert svc.stats()["group"]["quorum_failures"] > 0

    _restart(procs, dirs, "r1")
    _wait_synced(svc, 1)
    r = _settle(svc, [svc.kput(0, "k3", b"y")])
    assert r[0][0] == "ok"
    # the pre-outage acked write is still there
    r = _settle(svc, [svc.kget(0, "k")])
    assert r[0] == ("ok", b"v")


def test_promotion_fences_old_leader_and_loses_nothing(group):
    """In-place promotion: replica r1 takes over (promise round to a
    majority), after which the old leader's applies are nacked at the
    stale epoch — it can commit nothing (the sc.erl partition
    premise) — and every write the old leader acked is readable
    through the new one."""
    svc, procs, dirs, _ = group
    acked = {}
    futs = []
    for i in range(8):
        e, key, val = i % N_ENS, f"k{i}", b"v%d" % i
        futs.append(svc.kput(e, key, val))
        acked[(e, key)] = val
    _settle(svc, futs)
    assert all(f.value[0] == "ok" for f in futs)

    _, r1_repl, r1_client = procs["r1"][1], procs["r1"][1], procs["r1"][2]
    _, r2_repl, _ = procs["r2"]
    resp = _control(r1_repl, ("promote", [("127.0.0.1", r2_repl)]))
    assert resp[0] == "ok", resp
    new_ge = resp[1]
    assert new_ge > svc._ge

    # the deposed leader cannot commit anything anymore
    f = svc.kput(0, "stale", b"stale")
    try:
        _settle(svc, [f], flushes=3)
    except repgroup.DeposedError:
        pass
    assert f.done and f.value == "failed"
    assert svc._deposed

    # every previously-acked write is readable through the new leader
    async def read_back():
        from riak_ensemble_tpu import svcnode
        c = svcnode.ServiceClient("127.0.0.1", r1_client)
        await c.connect()
        for (e, key), val in acked.items():
            r = await c.kget(e, key, timeout=60.0)
            assert r == ("ok", val), (key, r)
        # and the stale-fenced write never became visible
        r = await c.kget(0, "stale", timeout=60.0)
        assert r == ("ok", NOTFOUND), r
        # the new leader commits new writes
        r = await c.kput(1, "post-promote", b"new", timeout=60.0)
        assert r[0] == "ok", r
        await c.close()

    import asyncio
    asyncio.run(read_back())


def test_partition_sigstop_excludes_then_heals(group):
    """A SIGSTOP'd replica is a network partition, not a death: the
    socket stays open and frames back up.  The leader must commit
    without it (ack deadline), and after SIGCONT the replica re-syncs
    and rejoins the quorum."""
    svc, procs, dirs, _ = group
    svc.ack_timeout = 3.0
    p1, _, _ = procs["r1"]

    _settle(svc, [svc.kput(0, "a", b"1")])
    p1.send_signal(signal.SIGSTOP)
    try:
        futs = [svc.kput(0, "b", b"2"), svc.kput(1, "c", b"3")]
        _settle(svc, futs)
        assert all(f.value[0] == "ok" for f in futs), \
            [f.value for f in futs]
    finally:
        p1.send_signal(signal.SIGCONT)
    _wait_synced(svc, 2)
    p2, _, _ = procs["r2"]
    p2.send_signal(signal.SIGKILL)
    p2.wait()
    futs = [svc.kget(0, "a"), svc.kget(0, "b"), svc.kget(1, "c")]
    _settle(svc, futs)
    assert [f.value for f in futs] == \
        [("ok", b"1"), ("ok", b"2"), ("ok", b"3")]


@pytest.mark.parametrize("seed", conftest.soak_seeds([1101, 1102]))
def test_repgroup_linearizable_under_host_nemesis(tmp_path, seed):
    """sc.erl over host failure domains: random put/get/CAS load
    against the leader while a nemesis kill -9s, SIGSTOPs and
    restarts the replica hosts.  Every acked write must be readable
    (KeyModel raises Violation on lost/stale/resurrected values);
    'failed' writes whose batch lost the host quorum are ambiguous
    (they applied on the surviving lanes) and join the plausible set
    via timeout_write — the same discipline sc.erl uses for timeouts.
    """
    rng = np.random.default_rng(seed)
    procs = {}
    dirs = {}
    for name in ("r1", "r2"):
        dirs[name] = str(tmp_path / name)
        procs[name] = _spawn_replica(dirs[name])
    svc = _make_leader(tmp_path, [procs["r1"][1], procs["r2"][1]],
                       ack_timeout=4.0)
    models = {}
    stopped = set()
    vals = iter(range(1, 100000))

    def model(e, k):
        return models.setdefault((e, k), KeyModel(f"{e}/k{k}"))

    try:
        for rnd in range(12):
            # nemesis
            r = rng.random()
            if r < 0.25:
                name = ("r1", "r2")[int(rng.integers(2))]
                p, _, _ = procs[name]
                if p.poll() is None and name not in stopped:
                    if rng.random() < 0.5:
                        p.send_signal(signal.SIGSTOP)
                        stopped.add(name)
                    else:
                        p.send_signal(signal.SIGKILL)
                        p.wait()
            elif r < 0.5:
                # heal: restart dead / continue stopped
                for name in ("r1", "r2"):
                    p, _, _ = procs[name]
                    if name in stopped:
                        p.send_signal(signal.SIGCONT)
                        stopped.discard(name)
                    elif p.poll() is not None:
                        _restart(procs, dirs, name)

            pending = []
            for _ in range(6):
                e = int(rng.integers(N_ENS))
                k = int(rng.integers(3))
                m = model(e, k)
                if rng.random() < 0.6:
                    v = next(vals)
                    op = m.invoke_write(v)
                    pending.append(("put", m, op,
                                    svc.kput(e, f"k{k}",
                                             v.to_bytes(4, "big"))))
                else:
                    pending.append(("get", m, None,
                                    svc.kget(e, f"k{k}")))
            for _ in range(8):
                if all(f.done for *_, f in pending):
                    break
                try:
                    svc.flush()
                except repgroup.DeposedError:  # pragma: no cover
                    raise
            for kind, m, op, f in pending:
                assert f.done
                res = f.value
                if kind == "put":
                    if isinstance(res, tuple) and res[0] == "ok":
                        m.ack_write(op)
                    else:
                        # host-quorum failure is ambiguous: the write
                        # applied on the surviving lanes
                        m.timeout_write(op)
                else:
                    if isinstance(res, tuple) and res[0] == "ok":
                        v = res[1]
                        m.ack_read(v if v is NOTFOUND
                                   else int.from_bytes(v, "big"))

        # quiesce: heal everything, then read back every key
        for name in ("r1", "r2"):
            p, _, _ = procs[name]
            if name in stopped:
                p.send_signal(signal.SIGCONT)
                stopped.discard(name)
            elif p.poll() is not None:
                _restart(procs, dirs, name)
        _wait_synced(svc, 2, deadline=120.0)
        pending = [(m, svc.kget(e, f"k{k}"))
                   for (e, k), m in models.items()]
        for _ in range(10):
            if all(f.done for _, f in pending):
                break
            svc.flush()
        for m, f in pending:
            assert f.done and isinstance(f.value, tuple) \
                and f.value[0] == "ok", f.value
            v = f.value[1]
            m.ack_read(v if v is NOTFOUND
                       else int.from_bytes(v, "big"))
    finally:
        svc.stop()
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGCONT)
                p.kill()


def test_leader_kill9_promote_replica_no_acked_loss(tmp_path):
    """The full machine-kill story with EVERY host a real OS process:
    promote r1 to leader, ack writes through its client port, kill -9
    the LEADER, promote r2 (promise round to the surviving majority +
    newest-state adoption), and every acked write must be readable —
    including the group-meta-in-the-commit-barrier property (review
    r4): the restarted/overtaken group can never mistake a
    data-bearing position for an older one."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    procs = {}
    dirs = {}
    try:
        for name in ("r1", "r2", "r3"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        _, r1_repl, r1_client = procs["r1"]
        _, r2_repl, r2_client = procs["r2"]
        _, r3_repl, _ = procs["r3"]

        resp = _control(r1_repl, ("promote", [("127.0.0.1", r2_repl),
                                              ("127.0.0.1", r3_repl)]))
        assert resp[0] == "ok", resp

        async def drive_writes():
            c = svcnode.ServiceClient("127.0.0.1", r1_client)
            await c.connect()
            acked = {}
            for i in range(10):
                e = i % N_ENS
                r = await c.kput(e, f"k{i}", b"v%d" % i, timeout=120.0)
                assert r[0] == "ok", r
                acked[(e, f"k{i}")] = b"v%d" % i
            await c.close()
            return acked

        acked = asyncio.run(drive_writes())

        # kill -9 the LEADER host
        p1, _, _ = procs["r1"]
        p1.send_signal(signal.SIGKILL)
        p1.wait()

        # promote r2: needs r3's grant (majority 2/3 with self)
        resp = _control(r2_repl, ("promote", [("127.0.0.1", r1_repl),
                                              ("127.0.0.1", r3_repl)]),
                        timeout=300.0)
        assert resp[0] == "ok", resp

        async def read_back_and_write():
            c = svcnode.ServiceClient("127.0.0.1", r2_client)
            await c.connect()
            for (e, key), val in acked.items():
                r = await c.kget(e, key, timeout=120.0)
                assert r == ("ok", val), (key, r)
            r = await c.kput(0, "post-failover", b"new", timeout=120.0)
            assert r[0] == "ok", r
            await c.close()

        asyncio.run(read_back_and_write())

        # the restarted OLD leader rejoins as a fenced replica and
        # re-syncs; after that, killing r3 leaves r2+r1 as the
        # quorum — the rejoined ex-leader carries its share
        _restart(procs, dirs, "r1")
        deadline = time.monotonic() + 120.0
        synced = False
        while time.monotonic() < deadline:
            st = _control(r2_repl, ("status",))
            # status: (status, role, promised, applied_ge, applied_seq)
            st1 = _control(r1_repl, ("status",))
            if st1[1] == "replica" and st1[3] == st[3] \
                    and st1[4] == st[4]:
                synced = True
                break
            time.sleep(1.0)
        assert synced, (st, st1)
        p3, _, _ = procs["r3"]
        p3.send_signal(signal.SIGKILL)
        p3.wait()

        async def final_check():
            c = svcnode.ServiceClient("127.0.0.1", r2_client)
            await c.connect()
            r = await c.kget(0, "post-failover", timeout=120.0)
            assert r == ("ok", b"new"), r
            r = await c.kput(1, "final", b"z", timeout=120.0)
            assert r[0] == "ok", r
            await c.close()

        asyncio.run(final_check())
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


def test_auto_failover_elects_new_leader_without_operator(tmp_path):
    """Automatic leader failover (the reference's peers self-elect on
    follower timeout; no operator in the loop): a cold-started group
    elects exactly one leader by itself, survives kill -9 of that
    leader by electing another within the failover window, loses no
    acked write, and a restarted ex-leader settles back in as a
    fenced replica."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    names = ("r1", "r2", "r3")
    repl_ports = {n: _free_port() for n in names}
    client_ports = {n: _free_port() for n in names}
    procs = {}
    dirs = {}

    def spawn(name):
        # restarts preserve BOTH ports and the failover/peer config —
        # a respawned host that can't campaign (or moved its client
        # port) would break the self-healing story mid-test
        others = [f"--peer=127.0.0.1:{repl_ports[o]}"
                  for o in names if o != name]
        return _spawn_replica(
            dirs[name], repl_port=repl_ports[name],
            client_port=client_ports[name],
            extra=["--auto-failover", "3.0"] + others)

    def roles():
        out = {}
        for n in names:
            p = procs[n][0]
            if p.poll() is not None:
                continue
            try:
                st = _control(repl_ports[n], ("status",), timeout=10.0)
                out[n] = st[1]
            except (OSError, ConnectionError):
                pass
        return out

    def wait_one_leader(deadline=90.0, exclude=()):
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            r = roles()
            leaders = [n for n, role in r.items() if role == "leader"]
            if len(leaders) == 1 and leaders[0] not in exclude:
                return leaders[0]
            time.sleep(1.0)
        raise AssertionError(f"no single leader emerged: {roles()}")

    try:
        for name in names:
            dirs[name] = str(tmp_path / name)
            procs[name] = spawn(name)

        # -- cold start: the group elects a leader BY ITSELF ----------
        leader = wait_one_leader()

        async def write(client_port, items):
            c = svcnode.ServiceClient("127.0.0.1", client_port)
            await c.connect()
            for (e, key), val in items.items():
                r = await c.kput(e, key, val, timeout=120.0)
                assert r[0] == "ok", (key, r)
            await c.close()

        acked = {(i % N_ENS, f"k{i}"): b"v%d" % i for i in range(8)}
        asyncio.run(write(procs[leader][2], acked))

        # -- kill -9 the elected leader: a successor self-promotes ----
        p, _, _ = procs[leader]
        p.send_signal(signal.SIGKILL)
        p.wait()
        new_leader = wait_one_leader(exclude=(leader,))
        assert new_leader != leader

        async def read_all(client_port):
            c = svcnode.ServiceClient("127.0.0.1", client_port)
            await c.connect()
            for (e, key), val in acked.items():
                r = await c.kget(e, key, timeout=120.0)
                assert r == ("ok", val), (key, r)
            r = await c.kput(0, "post", b"new", timeout=120.0)
            assert r[0] == "ok", r
            await c.close()

        asyncio.run(read_all(procs[new_leader][2]))

        # -- the restarted ex-leader (same auto-failover config)
        #    settles in as a fenced replica, not a duelist ------------
        procs[leader] = spawn(leader)
        end = time.monotonic() + 60.0
        while time.monotonic() < end:
            r = roles()
            if r.get(leader) == "replica" \
                    and r.get(new_leader) == "leader":
                break
            time.sleep(1.0)
        r = roles()
        assert r.get(leader) == "replica", r
        assert [n for n, role in r.items()
                if role == "leader"] == [new_leader], r
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


def test_group_client_follows_the_leader(tmp_path):
    """The leader-routing client role: GroupClient discovers the
    elected leader among the hosts' client ports, sticks to it, and
    re-discovers across a leader kill — not-leader rejections retry
    transparently (never dispatched), ambiguous disconnections
    surface to the caller."""
    import asyncio

    names = ("r1", "r2", "r3")
    repl_ports = {n: _free_port() for n in names}
    client_ports = {n: _free_port() for n in names}
    procs = {}
    dirs = {}

    def spawn(name):
        # restarts preserve BOTH ports and the failover/peer config —
        # a respawned host that can't campaign (or moved its client
        # port) would break the self-healing story mid-test
        others = [f"--peer=127.0.0.1:{repl_ports[o]}"
                  for o in names if o != name]
        return _spawn_replica(
            dirs[name], repl_port=repl_ports[name],
            client_port=client_ports[name],
            extra=["--auto-failover", "3.0"] + others)

    try:
        for name in names:
            dirs[name] = str(tmp_path / name)
            procs[name] = spawn(name)
        hosts = [("127.0.0.1", procs[n][2]) for n in names]

        async def scenario():
            gc = repgroup.GroupClient(hosts, op_timeout=120.0,
                                      discover_timeout=180.0)
            # discovery alone elects nothing — the group self-elects;
            # the client just has to find whoever won
            r = await gc.kput(0, "a", b"1")
            assert r[0] == "ok", r
            leader_addr = gc._leader_addr
            assert leader_addr is not None

            # kill the discovered leader: the next ops re-discover
            # the successor and proceed (the in-flight loss, if any,
            # would surface as DISCONNECTED — ambiguous by contract)
            victim = [n for n in names
                      if procs[n][2] == leader_addr[1]][0]
            p, _, _ = procs[victim]
            p.send_signal(signal.SIGKILL)
            p.wait()

            r = await gc.kget(0, "a")
            if r == ("error", "disconnected"):
                # the loss raced the read — ambiguous per contract;
                # a retried READ is always safe (and reads also ride
                # out a fresh leader's re-sync via retryable)
                r = await gc.kget(0, "a")
            assert r == ("ok", b"1"), r
            assert gc._leader_addr != leader_addr
            # the write may hit the new leader mid-re-sync ('failed' =
            # definitive no-ack) or lose a connection (ambiguous);
            # retrying an idempotent overwrite is the TEST's choice
            for _ in range(30):
                r = await gc.kput(0, "b", b"2")
                if isinstance(r, tuple) and r[0] == "ok":
                    break
                import asyncio as _a
                await _a.sleep(1.0)
            assert r[0] == "ok", r
            await gc.close()

        asyncio.run(scenario())
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
