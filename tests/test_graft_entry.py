"""Driver-contract guard: the multichip dry run (elections → K/V →
leader-down failover → joint-consensus reconfig → integrity sweep,
sharded-vs-single equivalence at every step) must keep passing on the
virtual 8-device CPU mesh the driver uses."""

import jax
import pytest


def test_dryrun_multichip_full_story():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
