"""Incremental (Merkle) catch-up for diverged repgroup replicas
(VERDICT r4 missing #3).

The reference heals peer divergence by tree exchange — cost
O(width·height·diffs), never O(keys) (synctree.erl:372-417,
riak_ensemble_exchange.erl:67-98).  Round 4's repgroup healed by full
snapshot install (every engine array + host mirror shipped per
re-sync).  These tests prove the round-5 tree-diff path:

- a restarted (briefly-dead) replica heals via the targeted patch,
  with measured re-sync bytes scaling with the DIFF, not the state,
- the healed replica then carries a quorum alone (zero acked loss),
- heavy divergence (a blank disk) falls back to the full snapshot.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import conftest  # noqa: F401

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import wire  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import WallRuntime  # noqa: E402

N_ENS = 8
N_SLOTS = 32


def _spawn_replica(data_dir: str, repl_port: int = 0,
                   client_port: int = 0):
    child = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from riak_ensemble_tpu.parallel import repgroup
        repgroup.main(["--n-ens", "{N_ENS}", "--group-size", "3",
                       "--n-slots", "{N_SLOTS}", "--fast",
                       "--repl-port", "{repl_port}",
                       "--client-port", "{client_port}",
                       "--data-dir", {data_dir!r}])
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True, env=env)
    line = p.stdout.readline()
    assert line, p.stderr.read()[-3000:]
    parts = dict(kv.split("=") for kv in line.split()[2:])
    return p, int(parts["repl"]), int(parts["client"])


def _make_leader(tmp_path, repl_ports):
    svc = repgroup.ReplicatedService(
        WallRuntime(), N_ENS, 1, N_SLOTS, group_size=3,
        peers=[("127.0.0.1", p) for p in repl_ports],
        ack_timeout=15.0, config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover()
    return svc


def _settle(svc, futs, flushes=10):
    for _ in range(flushes):
        if all(f.done for f in futs):
            break
        svc.flush()
    assert all(f.done for f in futs)
    return [f.value for f in futs]


def _wait_synced(svc, n, deadline=120.0):
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        svc.heartbeat()
        if svc.stats()["group"]["peers_synced"] >= n:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"peers never re-synced: {svc.stats()['group']}")


def test_restarted_replica_heals_by_tree_patch(tmp_path):
    procs, dirs = {}, {}
    try:
        for name in ("r1", "r2"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        svc = _make_leader(tmp_path,
                           [procs["r1"][1], procs["r2"][1]])
        acked = {}

        def put_ok(phase, n, size=200):
            futs = []
            for i in range(n):
                e, key = i % N_ENS, f"{phase}-{i}"
                val = (b"%s/%d/" % (phase.encode(), i)).ljust(size,
                                                             b"x")
                futs.append((e, key, val, svc.kput(e, key, val)))
            _settle(svc, [f for *_, f in futs])
            for e, key, val, f in futs:
                assert f.value[0] == "ok", (phase, key, f.value)
                acked[(e, key)] = val

        # a meaty base state, fully replicated
        put_ok("base", 48)
        _wait_synced(svc, 2)
        base_stats = dict(svc.stats()["group"])

        # kill r1, advance the group by a FEW slots (>= 2 flushes so
        # the restarted replica is strictly behind and freezes)
        p1 = procs["r1"][0]
        p1.send_signal(signal.SIGKILL)
        p1.wait()
        put_ok("gap-a", 2)
        put_ok("gap-b", 2)

        # restart r1 from its data_dir: catch-up must take the TREE
        # path, and its traffic must scale with the 4-slot diff, not
        # the 52-key state
        _, repl, client = procs["r1"]
        procs["r1"] = _spawn_replica(dirs["r1"], repl_port=repl,
                                     client_port=client)
        _wait_synced(svc, 2)
        g = svc.stats()["group"]
        assert g["tree_resyncs"] >= base_stats["tree_resyncs"] + 1, g
        full_bytes = len(wire.encode(
            ("install", 0, 0, repgroup.dump_state(svc),
             svc.core.cfg)))
        patch_bytes = (g["tree_resync_bytes"]
                       - base_stats["tree_resync_bytes"])
        assert 0 < patch_bytes < full_bytes / 3, \
            (patch_bytes, full_bytes)

        # the healed replica carries the quorum alone: kill r2
        p2 = procs["r2"][0]
        p2.send_signal(signal.SIGKILL)
        p2.wait()
        put_ok("post", 4)
        futs = [(e, key, val, svc.kget(e, key))
                for (e, key), val in acked.items()]
        _settle(svc, [f for *_, f in futs], flushes=14)
        for e, key, val, f in futs:
            assert f.value == ("ok", val), \
                f"acked write lost at {(e, key)}: {f.value!r}"
        assert svc.stats()["group"]["quorum_failures"] == 0
        svc.stop()
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


def test_blank_disk_falls_back_to_full_install(tmp_path):
    """A replacement host with an empty disk diverges in (almost)
    every ensemble: the probe's >50%-diff gate must route it to the
    full snapshot — the tree path is an optimization, never the only
    door."""
    import shutil

    procs, dirs = {}, {}
    try:
        for name in ("r1", "r2"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        svc = _make_leader(tmp_path,
                           [procs["r1"][1], procs["r2"][1]])
        futs = [svc.kput(e, f"k{i}", b"v%d" % i)
                for i in range(2 * N_ENS) for e in [i % N_ENS]]
        _settle(svc, futs)
        assert all(f.value[0] == "ok" for f in futs)
        _wait_synced(svc, 2)
        before = dict(svc.stats()["group"])

        # kill r1, WIPE its disk, advance, restart blank on its ports
        p1 = procs["r1"][0]
        p1.send_signal(signal.SIGKILL)
        p1.wait()
        shutil.rmtree(dirs["r1"])
        _settle(svc, [svc.kput(0, "extra", b"x")])
        _settle(svc, [svc.kput(1, "extra", b"x")])
        _, repl, client = procs["r1"]
        procs["r1"] = _spawn_replica(dirs["r1"], repl_port=repl,
                                     client_port=client)
        _wait_synced(svc, 2)
        g = svc.stats()["group"]
        assert g["resyncs"] > before["resyncs"], (before, g)
        assert g["tree_resyncs"] == before["tree_resyncs"], \
            (before, g)
        svc.stop()
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
