"""Pipelined async service execution (the two-phase launch split):
overlap of device rounds with host resolve, ordering guarantees,
deferred corruption semantics, the execute_async surface, and the
donated-state step variants.

The overlap test injects d2h latency through the ``_fetch_packed``
seam (the packed vector "arrives" DELAY after its enqueue, like a
transfer riding a slow link): at depth 1 every flush eats the full
delay; at depth 2 the delay of batch N runs under batch N+1's
enqueue + dwell, roughly halving wall time.  A regression that
silently serializes the pipeline (settle-before-enqueue) collapses
the ratio to ~1 and fails fast — the tier-1 guard the bench's
``serial_ops_per_sec`` A/B mirrors at full shapes.
"""

import time
import warnings

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402


def make_service(n_ens=4, n_peers=3, n_slots=8, depth=2, max_k=2,
                 runtime=None, **kw):
    runtime = runtime if runtime is not None else Runtime(seed=77)
    svc = BatchedEnsembleService(runtime, n_ens, n_peers, n_slots,
                                 tick=None, max_ops_per_tick=max_k,
                                 config=fast_test_config(),
                                 pipeline_depth=depth, **kw)
    return runtime, svc


def drain(svc):
    while any(svc.queues):
        svc.flush()
    svc.flush()  # idle flush settles the in-flight tail


class _DelayedService(BatchedEnsembleService):
    """Injected d2h latency: the packed result is 'on the host' only
    DELAY seconds after its enqueue — the transfer-time model the
    pipeline exists to hide."""

    DELAY = 0.04

    def __init__(self, *a, **kw):
        self._ready_at = {}
        super().__init__(*a, **kw)

    def _launch_enqueue(self, *a, **kw):
        fl = super()._launch_enqueue(*a, **kw)
        self._ready_at[id(fl)] = time.perf_counter() + self.DELAY
        return fl

    def _fetch_packed(self, fl):
        ready = self._ready_at.pop(id(fl), 0.0)
        while time.perf_counter() < ready:
            time.sleep(0.001)
        return super()._fetch_packed(fl)


def _timed_burst(depth: int, batches: int = 8) -> float:
    """Wall time to serve `batches` single-round flushes of queued
    keyed puts under injected d2h delay."""
    runtime = Runtime(seed=7)
    svc = _DelayedService(runtime, 2, 3, 8, tick=None,
                          max_ops_per_tick=1,
                          config=fast_test_config(),
                          pipeline_depth=depth)
    # election launch outside the timed region
    svc.flush()
    svc.flush()
    futs = [svc.kput(0, f"k{j}", b"v") for j in range(batches)]
    t0 = time.perf_counter()
    drain(svc)
    elapsed = time.perf_counter() - t0
    assert all(f.done and f.value[0] == "ok" for f in futs)
    return elapsed


def test_depth2_overlaps_injected_d2h_delay():
    """THE serialization guard: depth 2 must genuinely overlap batch
    N's in-flight transfer with batch N+1's enqueue — wall time well
    under the depth-1 serial sum.  Generous margin (0.75) over the
    ideal ~0.5x keeps slow-CI noise out."""
    t1 = _timed_burst(depth=1)
    t2 = _timed_burst(depth=2)
    assert t2 < 0.75 * t1, (t1, t2)


def test_pipelined_results_resolve_in_submission_order():
    runtime, svc = make_service(max_k=1, n_slots=16)
    order = []
    futs = []
    for j in range(10):
        f = svc.kput(0, f"k{j}", b"v%d" % j)
        f.add_waiter(lambda _r, j=j: order.append(j))
        futs.append(f)
    drain(svc)
    assert all(f.done and f.value[0] == "ok" for f in futs)
    assert order == sorted(order), order
    # and the data is right
    g = svc.kget(0, "k3")
    drain(svc)
    assert g.value == ("ok", b"v3")


def test_latency_marks_split_by_mode():
    """Depth-1 records keep the serial device_d2h mark; pipelined
    records carry enqueue/inflight_wait (+ the flush-side resolve),
    the fields the overlap analysis needs."""
    _rt, svc1 = make_service(depth=1)
    svc1.kput(0, "k", b"v")
    drain(svc1)
    keys1 = {k for r in svc1.lat_records for k in r}
    assert "device_d2h" in keys1 and "inflight_wait" not in keys1
    assert {"enqueue", "resolve", "wal", "queue_wait"} <= keys1

    _rt, svc2 = make_service(depth=2, max_k=1)
    for j in range(4):
        svc2.kput(0, f"k{j}", b"v")
    drain(svc2)
    keys2 = {k for r in svc2.lat_records for k in r}
    assert {"enqueue", "inflight_wait", "resolve",
            "queue_wait"} <= keys2
    assert "device_d2h" not in keys2
    bd = svc2.latency_breakdown()
    assert "inflight_wait" in bd and "enqueue" in bd
    assert svc2.stats()["pipeline_depth"] == 2
    assert svc2.stats()["launches_in_flight"] == 0


class _TracedService(BatchedEnsembleService):
    """Event-order probe: enqueue/resolve boundaries of every launch."""

    def __init__(self, *a, **kw):
        self.events = []
        self._seq = 0
        super().__init__(*a, **kw)

    def _launch_enqueue(self, *a, **kw):
        fl = super()._launch_enqueue(*a, **kw)
        self._seq += 1
        self.events.append(("enq", self._seq))
        return fl

    def _launch_resolve(self, fl, wait_key="device_d2h"):
        out = super()._launch_resolve(fl, wait_key)
        self.events.append(("res", None))
        return out


def test_corruption_deferral_repairs_before_next_ack():
    """The corrupt planes are inspected one round late under the
    pipeline (batch N+1's enqueue precedes batch N's resolve), but
    the exchange still lands BEFORE batch N+1's results are acked —
    the flagged-ensemble-repaired-before-its-next-ack contract."""
    runtime = Runtime(seed=9)
    svc = _TracedService(runtime, 4, 3, 8, tick=None,
                         max_ops_per_tick=1,
                         config=fast_test_config(), pipeline_depth=2)

    def trace(kind, _payload):
        if kind == "svc_exchange":
            svc.events.append(("trace", kind))
    runtime.trace = trace
    futs = {}
    for e in range(4):
        futs[e] = svc.kput(e, "k", b"v")
    drain(svc)
    assert all(f.done and f.value[0] == "ok" for f in futs.values())

    # out-of-band damage on peer 2's copy of "k" in ensemble 0 (only
    # ensemble 0 is read below, so only its damage can be detected)
    slot_k = svc.key_slot[0]["k"]
    svc.state = svc.state._replace(
        obj_val=svc.state.obj_val.at[0, 2, slot_k].set(424242))

    # two read batches through the pipeline: batch 1's read trips the
    # integrity gate; its corrupt plane is inspected at resolve —
    # after batch 2's enqueue — and the exchange dispatches before
    # batch 2's futures resolve.  Expire the leases first: a leased
    # fast read would serve the host mirror and never take the device
    # round whose integrity gate this test exercises.
    svc.lease_until[:] = 0.0
    svc.events.clear()

    def on_ack(j):
        return lambda _r: svc.events.append(("ack", j))
    g1 = svc.kget(0, "k")
    g1.add_waiter(on_ack(1))
    g2 = svc.kget(0, "k")
    g2.add_waiter(on_ack(2))
    drain(svc)
    assert g1.value == ("ok", b"v") and g2.value == ("ok", b"v")
    assert svc.corruptions > 0
    ev = svc.events
    kinds = [k for k, _v in ev]
    # pipeline really ran: both enqueues before the first resolve
    assert kinds.index("res") > 1 and kinds[0] == "enq"
    exch = next(i for i, (k, v) in enumerate(ev)
                if (k, v) == ("trace", "svc_exchange"))
    ack2 = next(i for i, (k, v) in enumerate(ev) if (k, v) == ("ack", 2))
    assert exch < ack2, ev
    # the sweep healed the replica
    node_bad, leaf_bad = eng.verify_trees(svc.state)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def _exec_planes(n_ens, n_slots, k, seed=0):
    rng = np.random.default_rng(seed)
    kind = rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)).astype(np.int32)
    slot = rng.integers(0, n_slots, (k, n_ens)).astype(np.int32)
    val = rng.integers(1, 1 << 20, (k, n_ens)).astype(np.int32)
    return kind, slot, val


def test_execute_async_pipeline_and_sync_interleave():
    svc = BatchedEnsembleService(WallRuntime(), 8, 3, 8, tick=None,
                                 max_ops_per_tick=4,
                                 config=fast_test_config(),
                                 pipeline_depth=2)
    kind, slot, val = _exec_planes(8, 8, 4)
    futs = [svc.execute_async(kind, slot, val) for _ in range(5)]
    # depth bound: at most pipeline_depth launches unsettled
    assert len(svc._inflight_launches) <= 2
    # a synchronous execute settles everything in flight first, so
    # every earlier async result resolves before it returns
    committed, get_ok, _f, _v = svc.execute(kind, slot, val)
    assert all(f.done for f in futs)
    assert (committed | get_ok).all()
    for f in futs:
        c, g, _fo, _va = f.value
        assert (c | g).all()
    # idle flush settles a lone trailing async batch
    tail = svc.execute_async(kind, slot, val)
    svc.flush()
    assert tail.done
    assert svc.stats()["launches_in_flight"] == 0
    svc.stop()


def test_execute_async_matches_execute_results():
    """Same op stream through a depth-2 async service and a depth-1
    sync service lands identical result planes (the pipeline is pure
    scheduling, not semantics)."""
    outs = {}
    for depth in (1, 2):
        svc = BatchedEnsembleService(WallRuntime(), 6, 3, 8, tick=None,
                                     max_ops_per_tick=4,
                                     config=fast_test_config(),
                                     pipeline_depth=depth)
        res = []
        for i in range(4):
            kind, slot, val = _exec_planes(6, 8, 4, seed=i)
            if depth == 1:
                res.append(svc.execute(kind, slot, val))
            else:
                res.append(svc.execute_async(kind, slot, val))
        svc.flush()
        if depth == 2:
            assert all(f.done for f in res)
            res = [f.value for f in res]
        outs[depth] = res
        svc.stop()
    for a, b in zip(outs[1], outs[2]):
        for pa, pb in zip(a, b):
            np.testing.assert_array_equal(np.asarray(pa),
                                          np.asarray(pb))


def test_full_step_donate_matches_plain():
    """The donated-state step program computes the same protocol math
    as the plain one (donation only changes buffer aliasing)."""
    e, m, s, k = 6, 3, 8, 4
    up = jax.numpy.ones((e, m), bool)
    elect = jax.numpy.ones((e,), bool)
    cand = jax.numpy.zeros((e,), jax.numpy.int32)
    rng = np.random.default_rng(3)
    kind = jax.numpy.asarray(
        rng.choice([eng.OP_PUT, eng.OP_GET], (k, e)), jax.numpy.int32)
    slot = jax.numpy.asarray(rng.integers(0, s, (k, e)), jax.numpy.int32)
    val = jax.numpy.asarray(rng.integers(1, 99, (k, e)), jax.numpy.int32)
    lease = jax.numpy.zeros((k, e), bool)

    st_a = eng.init_state(e, m, s)
    st_b = eng.init_state(e, m, s)
    for _ in range(3):
        st_a, won_a, res_a = eng.full_step(
            st_a, elect, cand, kind, slot, val, lease, up)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # CPU may ignore donation
            st_b, won_b, res_b = eng.full_step_donate(
                st_b, elect, cand, kind, slot, val, lease, up)
        elect = jax.numpy.zeros((e,), bool)
    np.testing.assert_array_equal(np.asarray(won_a), np.asarray(won_b))
    for fa, fb in zip(res_a, res_b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    for fa, fb in zip(st_a, st_b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_service_with_donation_enabled(monkeypatch):
    """RETPU_DONATE=1 routes launches through the donated programs;
    the keyed surface stays correct (CPU backends may fall back to a
    copy — the warning is the fallback, not an error)."""
    monkeypatch.setenv("RETPU_DONATE", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        runtime, svc = make_service(depth=2, max_k=2)
        assert svc._donate
        futs = [svc.kput(e, "k", b"v%d" % e) for e in range(svc.n_ens)]
        drain(svc)
        assert all(f.done and f.value[0] == "ok" for f in futs)
        gets = [svc.kget(e, "k") for e in range(svc.n_ens)]
        drain(svc)
        assert [g.value for g in gets] == \
            [("ok", b"v%d" % e) for e in range(svc.n_ens)]


def test_pipelined_flush_with_timer_runtime():
    """The tick-driven service composes with the pipeline: futures
    resolve through timer flushes exactly as at depth 1."""
    runtime = Runtime(seed=21)
    svc = BatchedEnsembleService(runtime, 4, 3, 8, tick=0.005,
                                 config=fast_test_config(),
                                 pipeline_depth=2)
    futs = [svc.kput(e, "k", b"x") for e in range(4)]
    for f in futs:
        assert runtime.await_future(f, 5.0)[0] == "ok"
    g = svc.kget(2, "k")
    assert runtime.await_future(g, 5.0) == ("ok", b"x")
    svc.stop()


def test_single_lane_replicated_service_pipelines():
    """A link-less ReplicatedService (replica role / single lane)
    forwards through the split halves unchanged at depth 2."""
    from riak_ensemble_tpu.parallel.repgroup import ReplicatedService

    runtime = WallRuntime()
    svc = ReplicatedService(runtime, 4, 1, 8, group_size=1,
                            config=fast_test_config(),
                            pipeline_depth=2, max_ops_per_tick=1)
    futs = [svc.kput(0, f"k{j}", b"v%d" % j) for j in range(4)]
    drain(svc)
    assert all(f.done and f.value[0] == "ok" for f in futs)
    g = svc.kget(0, "k2")
    drain(svc)
    assert g.value == ("ok", b"v2")
    svc.stop()


def test_wal_error_does_not_abandon_later_launches(tmp_path):
    """A WAL-append failure settling launch N must not poison launch
    N+1: N's device commits are real (its clients get 'failed' — the
    allowed unacked outcome), but N+1's chain is healthy and its ops
    must settle normally once the disk recovers; abandoning it would
    recycle slots the device still populates."""
    runtime = Runtime(seed=5)
    svc = BatchedEnsembleService(runtime, 2, 3, 8, tick=None,
                                 max_ops_per_tick=1,
                                 config=fast_test_config(),
                                 pipeline_depth=2,
                                 data_dir=str(tmp_path))
    svc.flush()  # election round out of the way
    svc.flush()

    real_log = svc._wal.log
    fail_next = {"n": 1}

    def flaky_log(recs):
        if fail_next["n"]:
            fail_next["n"] -= 1
            raise OSError("disk full")
        return real_log(recs)
    svc._wal.log = flaky_log

    f1 = svc.kput(0, "a", b"v1")
    f2 = svc.kput(0, "b", b"v2")
    with pytest.raises(OSError):
        drain(svc)
    # f1's commit could not be acked (WAL failed) — allowed outcome
    assert f1.done and f1.value == "failed"
    # f2 rode a healthy chain and a healthy disk: it must be acked
    assert f2.done and f2.value[0] == "ok", f2.value
    g = svc.kget(0, "b")
    drain(svc)
    assert g.value == ("ok", b"v2")
    svc.stop()
