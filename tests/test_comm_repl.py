"""Commutative replication lane (ISSUE 18 tentpole).

Table-fun RMWs whose funs commute (add/sub) or form a semilattice
(max/min/band/bor) replicate as per-(ensemble, slot) COALESCED merge
cells — a "m" wire entry carrying a merge section next to the ordered
delta half — applied by replicas as a lattice merge against their own
current value, with a pure-merge frame acked after the WAL sync but
before the device scatter (the §18 early ack).  These tests pin the
load-bearing contracts:

- classification: set/bxor/put_if_absent stay ORDERED; the fold is
  int32-exact (sub normalizes into add of the negated operand);
- build_comm_entry qualification: a column ships merge cells only
  when EVERY committed cell is a mergeable RMW and each slot sees a
  single merge class — anything else falls to the ordered half,
  and the native C fold is byte-identical to the Python fold;
- the replica apply: merge sections carry their own CRC, all-or-
  nothing with the run; version vectors land bit-equal to the
  sequenced apply (the delta-lane equivalence harness);
- RETPU_COMM_REPL=0 is the ordered oracle arm: zero "m" entries,
  same results, same final KV state;
- kmodify_many enqueue-side coalescing: duplicate commutative keys
  fold into one device row whose shared version is CAS-usable;
- ServiceClient never auto-retries kmodify/kmodify_many on an
  ambiguous disconnect (early acks make RMW storms the hot
  ambiguous-drop shape — a silent retry would double-apply);
- randomized convergence: drop/RTT churn + a replica_apply_pre_ack
  crash-kill, with CounterModel holding the final-sum obligation
  across restart and handoff.
"""

import asyncio
import os
import struct
import time

import numpy as np
import pytest

import conftest  # noqa: F401

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import faults, funref, svcnode, wire  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.linearizability import (  # noqa: E402
    CounterModel, KeyModel)
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup, resolve_native  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

from test_repl_delta import (  # noqa: E402
    _assert_lanes_equal, _group, _plain_core, _settle, _stop)

N_ENS = 4
N_SLOTS = 8


def _counter_val(res):
    """kget result -> int counter value (engine encodes 0 as
    NOTFOUND — the inline tombstone convention)."""
    assert res[0] == "ok", res
    return 0 if res[1] is NOTFOUND or res[1] == NOTFOUND else int(res[1])


# -- classification ----------------------------------------------------------


def test_funref_classification_table():
    """The §18 classification is a frozen contract: commutative and
    semilattice funs merge, everything order-sensitive stays ordered
    — set (last-writer), bxor (self-inverse: merging would lose
    parity of application count) and put_if_absent (first-writer)."""
    assert funref.merge_class(funref.RMW_ADD) == funref.MERGE_ADD
    assert funref.merge_class(funref.RMW_SUB) == funref.MERGE_ADD
    assert funref.merge_class(funref.RMW_MAX) == funref.MERGE_MAX
    assert funref.merge_class(funref.RMW_MIN) == funref.MERGE_MIN
    assert funref.merge_class(funref.RMW_BAND) == funref.MERGE_AND
    assert funref.merge_class(funref.RMW_BOR) == funref.MERGE_OR
    for code in (funref.RMW_SET, funref.RMW_BXOR, funref.RMW_PIA):
        assert funref.merge_class(code) is None
        assert funref.RMW_CLASS[code] == funref.ORDERED
    # the replication-side mergeable LUT agrees with the table
    for code in range(9):
        assert bool(repgroup._RMW_MERGEABLE[code]) == \
            (funref.merge_class(code) is not None), code


def test_fold_int32_exact_and_sub_normalization():
    """The fold lives in int32-wraparound arithmetic — bit-equal to
    chaining the ops through the engine — and sub folds as add of
    the negated operand (one merge class per slot)."""
    i32 = funref.i32
    # wraparound: INT32_MAX + 1 folds to INT32_MIN
    acc = funref.fold_seed(funref.RMW_ADD, 2 ** 31 - 1)
    acc = funref.fold_operand(funref.RMW_ADD, acc, 1)
    assert acc == -2 ** 31
    # sub seeds negated, folds negated: cur - a - b == cur + (-(a+b))
    acc = funref.fold_seed(funref.RMW_SUB, 5)
    assert acc == -5
    acc = funref.fold_operand(funref.RMW_SUB, acc, 7)
    assert acc == -12
    assert funref.merge_apply(funref.MERGE_ADD, 100, acc) == 88
    # INT32_MIN negation wraps onto itself — still exact
    assert funref.fold_seed(funref.RMW_SUB, -2 ** 31) == -2 ** 31
    # semilattice folds are idempotent
    acc = funref.fold_seed(funref.RMW_MAX, 9)
    acc = funref.fold_operand(funref.RMW_MAX, acc, 9)
    assert acc == 9
    assert funref.merge_apply(funref.MERGE_MAX, 12, acc) == 12
    assert funref.merge_apply(funref.MERGE_AND, 0b1101, 0b0111) == 0b0101
    assert funref.merge_apply(funref.MERGE_OR, 0b1000, 0b0011) == 0b1011
    assert i32(2 ** 31) == -2 ** 31


# -- build_comm_entry qualification ------------------------------------------


def _comm_arrays(k=4):
    committed = np.zeros((k, N_ENS), bool)
    kind = np.zeros((k, N_ENS), np.int32)
    slot = np.zeros((k, N_ENS), np.int32)
    val = np.zeros((k, N_ENS), np.int32)
    exp_e = np.zeros((k, N_ENS), np.int32)
    value = np.zeros((k, N_ENS), np.int32)
    q = np.ones((N_ENS,), bool)
    return committed, kind, slot, val, exp_e, value, q


def test_build_comm_entry_qualification_and_coalescing():
    """Column 1 (all adds, two slots) ships 2 merge cells for 4 ops;
    column 2 (add-then-max on ONE slot: mixed classes) and column 3
    (ordered set) stay in the ordered half byte-for-byte."""
    committed, kind, slot, val, exp_e, value, q = _comm_arrays()
    # col 1: adds — rows 0..2 hit slot 3, row 3 hits slot 5
    for j, (s, v) in enumerate([(3, 5), (3, 9), (3, -2), (5, 7)]):
        committed[j, 1] = True
        kind[j, 1] = eng.OP_RMW
        exp_e[j, 1] = funref.RMW_ADD
        slot[j, 1] = s
        val[j, 1] = v
    # col 2: add then max on the SAME slot — mixed classes, ordered
    for j, code in enumerate([funref.RMW_ADD, funref.RMW_MAX]):
        committed[j, 2] = True
        kind[j, 2] = eng.OP_RMW
        exp_e[j, 2] = code
        slot[j, 2] = 4
        val[j, 2] = 10 + j
    # col 3: a single ordered set — never a candidate
    committed[0, 3] = True
    kind[0, 3] = eng.OP_RMW
    exp_e[0, 3] = funref.RMW_SET
    slot[0, 3] = 2
    val[0, 3] = 77

    out = repgroup.build_comm_entry(
        1, 4, committed, value, kind, slot, val, exp_e, q, [],
        n_slots=N_SLOTS)
    assert out is not None
    entry, crc, nbytes, n_cells, n_ops = out
    assert entry[0] == "m" and n_cells == 2 and n_ops == 4
    # ordered half keeps the 3 disqualified cells (col 2 + col 3)
    assert int(entry[3]) == 3
    ord_cols = np.frombuffer(entry[6].buf, np.uint16)
    assert 1 not in ord_cols.tolist()
    # merge section: one column, two cells in first-seen slot order,
    # folded operands, rank/j of each slot's LAST committed op
    assert int(entry[14]) == 2
    assert np.frombuffer(entry[15].buf, np.uint16).tolist() == [1]
    assert np.frombuffer(entry[16].buf, np.uint16).tolist() == [2]
    assert np.frombuffer(entry[17].buf, np.uint16).tolist() == [4]
    assert np.frombuffer(entry[18].buf, np.uint8).tolist() == [3, 5]
    assert np.frombuffer(entry[19].buf, np.uint8).tolist() == \
        [funref.MERGE_ADD, funref.MERGE_ADD]
    assert np.frombuffer(entry[20].buf, np.int32).tolist() == [12, 7]
    assert np.frombuffer(entry[21].buf, np.uint8).tolist() == [2, 3]
    assert np.frombuffer(entry[22].buf, np.uint8).tolist() == [2, 3]
    # the ack CRC chains both halves
    assert crc == repgroup._crc_chain(int(entry[13]), int(entry[23]))

    # no mergeable column at all -> None (the caller ships plain "d",
    # which is what keeps the off arm byte-identical by construction)
    committed[:, 1] = False
    assert repgroup.build_comm_entry(
        1, 4, committed, value, kind, slot, val, exp_e, q, [],
        n_slots=N_SLOTS) is None
    # a put anywhere in an otherwise-mergeable column disqualifies it
    committed2, kind2, slot2, val2, exp_e2, value2, q2 = _comm_arrays()
    committed2[0, 0] = committed2[1, 0] = True
    kind2[0, 0] = eng.OP_RMW
    exp_e2[0, 0] = funref.RMW_ADD
    kind2[1, 0] = eng.OP_PUT
    assert repgroup.build_comm_entry(
        1, 4, committed2, value2, kind2, slot2, val2, exp_e2, q2, [],
        n_slots=N_SLOTS) is None


def _entry_canon(entry):
    return [bytes(x.buf) if isinstance(x, wire.Raw) else x
            for x in entry]


def test_build_comm_entry_native_python_parity():
    """The C fold (resolvekernel.cc retpu_comm_fold) and the Python
    fold must emit byte-identical entries over randomized committed
    planes — mixed fun codes, repeated slots, disqualified columns."""
    nat = resolve_native.get()
    if nat is None:
        pytest.skip("native resolve library unavailable")
    rng = np.random.default_rng(1808)
    built = 0
    for _ in range(60):
        k = int(rng.integers(1, 7))
        committed = rng.random((k, N_ENS)) < 0.6
        kind = np.where(rng.random((k, N_ENS)) < 0.85,
                        eng.OP_RMW, eng.OP_PUT).astype(np.int32)
        exp_e = rng.integers(0, 9, (k, N_ENS)).astype(np.int32)
        slot = rng.integers(0, N_SLOTS, (k, N_ENS)).astype(np.int32)
        val = rng.integers(-2 ** 31, 2 ** 31, (k, N_ENS),
                           dtype=np.int64).astype(np.int32)
        value = np.zeros((k, N_ENS), np.int32)
        q = np.ones((N_ENS,), bool)
        py = repgroup.build_comm_entry(
            1, k, committed, value, kind, slot, val, exp_e, q, [],
            n_slots=N_SLOTS, native=None)
        nv = repgroup.build_comm_entry(
            1, k, committed, value, kind, slot, val, exp_e, q, [],
            n_slots=N_SLOTS, native=nat)
        if py is None:
            assert nv is None
            continue
        assert nv is not None
        assert _entry_canon(py[0]) == _entry_canon(nv[0])
        assert py[1:] == nv[1:]
        built += 1
    assert built >= 10, "fuzz never produced a qualifying flush"


# -- replica apply of "m" entries --------------------------------------------


def _one_cell_entry(operand=14, nops=2):
    """A minimal qualifying flush: two adds on (ens 1, slot 3)."""
    committed, kind, slot, val, exp_e, value, q = _comm_arrays(k=2)
    for j, v in enumerate([operand - 9, 9] if nops == 2 else [operand]):
        committed[j, 1] = True
        kind[j, 1] = eng.OP_RMW
        exp_e[j, 1] = funref.RMW_ADD
        slot[j, 1] = 3
        val[j, 1] = v
    out = repgroup.build_comm_entry(
        1, 2, committed, value, kind, slot, val, exp_e, q, [],
        n_slots=N_SLOTS)
    assert out is not None
    return out


def test_merge_section_crc_violation_nacks(tmp_path):
    """A flipped byte in the MERGE section (its own CRC, separate
    from the ordered half's) must nack and leave the lane untouched;
    the replayed good entry applies the lattice merge and advances
    the slot's seq counter by the ops the cell absorbed."""
    svc, core = _plain_core(tmp_path)
    entry, crc, _nbytes, n_cells, n_ops = _one_cell_entry()
    assert entry[0] == "m" and int(entry[3]) == 0
    bad_ops = np.frombuffer(entry[20].buf, np.int32).copy()
    bad_ops[0] ^= 0xFF
    bad = entry[:20] + (wire.Raw(bad_ops),) + entry[21:]
    r = core.handle_abatch(("abatch", 0, [bad]))
    assert r[0] == "nack" and r[1] == "crc"
    assert core.applied_seq == 0
    assert int(np.asarray(svc.state.obj_val)[1, 0, 3]) == 0
    ctr0 = int(np.asarray(svc.state.obj_seq_ctr)[1])
    r = core.handle_abatch(("abatch", 0, [entry]))
    assert r == ("applied", 0, 1, repgroup._crc_chain(0, crc))
    assert int(np.asarray(svc.state.obj_val)[1, 0, 3]) == 14
    # seq discipline: the counter advances by the ABSORBED op count,
    # so version vectors land bit-equal to the sequenced apply
    assert int(np.asarray(svc.state.obj_seq_ctr)[1]) == ctr0 + n_ops
    svc.stop()


def test_merge_section_bounds_violations_nack(tmp_path):
    """Hostile merge sections (out-of-range slot, rank >= nops) nack
    all-or-nothing — CRC-valid but semantically broken frames must
    not partially apply."""
    import zlib

    svc, core = _plain_core(tmp_path)
    entry, _crc, _nb, _c, _o = _one_cell_entry()

    def rebuild(idx, arr):
        """Swap section idx and RESTAMP the merge CRC so only the
        semantic validation can reject it."""
        out = list(entry)
        out[idx] = wire.Raw(np.ascontiguousarray(arr))
        mcrc = 0
        for i in range(15, 23):
            mcrc = zlib.crc32(bytes(out[i].buf), mcrc)
        out[23] = mcrc
        return tuple(out)

    # slot out of range
    bad_slot = np.frombuffer(entry[18].buf, np.uint8).copy()
    bad_slot[0] = N_SLOTS + 3
    r = core.handle_abatch(("abatch", 0, [rebuild(18, bad_slot)]))
    assert r[0] == "nack", r
    # rank >= nops
    bad_rl = np.frombuffer(entry[21].buf, np.uint8).copy()
    bad_rl[0] = 9
    r = core.handle_abatch(("abatch", 0, [rebuild(21, bad_rl)]))
    assert r[0] == "nack", r
    assert core.applied_seq == 0
    assert int(np.asarray(svc.state.obj_val)[1, 0, 3]) == 0
    svc.stop()


# -- leader/replica end-to-end -----------------------------------------------


def _mixed_results(svc):
    """A deterministic mixed workload (commutative, semilattice,
    ordered, puts, deletes); returns (pre, many, post, gets) — the
    results before the duplicate-key kmodify_many (bit-equal across
    arms, versions included), the kmodify_many group itself plus the
    ops after it (status-equal: coalescing commits FEWER ops, so the
    ensemble's seq counter legitimately diverges downstream), and
    the final reads (value-equal — the converged KV state)."""
    pre = []
    pre += _settle(svc, [svc.kput(e, f"k{e}", b"v%d" % e)
                         for e in range(N_ENS)])
    pre += _settle(svc, [svc.kmodify(e, f"c{e}",
                                     funref.ref("rmw:add", 7), 0)
                         for e in range(N_ENS)])
    pre += _settle(svc, [svc.kmodify(0, "c0",
                                     funref.ref("rmw:sub", 3), 0),
                         svc.kmodify(1, "c1",
                                     funref.ref("rmw:max", 50), 0),
                         svc.kmodify(2, "c2",
                                     funref.ref("rmw:bxor", 5), 0)])
    many = _settle(svc, [svc.kmodify_many(
        3, ["c3", "d3", "c3", "c3"], funref.ref("rmw:add", 2), 0)])[0]
    post = _settle(svc, [svc.kdelete(3, "k3")])
    gets = _settle(svc, [svc.kget(e, f"c{e}") for e in range(N_ENS)])
    gets += _settle(svc, [svc.kget(3, "d3"), svc.kget(0, "k0"),
                          svc.kget(3, "k3")])
    return pre, many, post, gets


def test_comm_on_off_equivalence_and_metrics(tmp_path):
    """THE oracle arm: RETPU_COMM_REPL=0 runs the identical workload
    through the plain ordered delta lane — zero "m" entries, same
    client results, same final KV values — while the comm arm ships
    merge entries; both converge replica lanes bit-equal, and the
    §18 metric families are registered on BOTH arms."""
    svc_on, srvs_on = _group(tmp_path / "on")
    svc_off, srvs_off = _group(tmp_path / "off")
    svc_off._comm_repl = False
    try:
        pre_on, many_on, post_on, gets_on = _mixed_results(svc_on)
        pre_off, many_off, post_off, gets_off = _mixed_results(svc_off)
        # bit-equal up to the coalescing point, versions included
        assert pre_on == pre_off
        # the dup-key group and everything after: status-equal (the
        # comm arm committed fewer ops, so ensemble 3's seq counter
        # legitimately runs behind)
        assert [x[0] for x in many_on] == [x[0] for x in many_off]
        assert [x[0] for x in post_on] == [x[0] for x in post_off]
        # the converged KV state is value-identical
        assert gets_on == gets_off
        g_on = svc_on.stats()["group"]
        g_off = svc_off.stats()["group"]
        assert g_on["comm_repl"] is True
        assert g_off["comm_repl"] is False
        assert g_on["repl_merge_entries"] > 0, g_on
        assert g_on["repl_merge_ops"] >= g_on["repl_merge_cells"] > 0
        # the off arm never builds a merge section — bit-identity
        # with the pre-§18 stream is by construction
        assert g_off["repl_merge_entries"] == 0, g_off
        assert g_off["repl_merge_cells"] == 0
        assert g_off["repl_early_acks"] == 0
        # always-registered families (zeroed on the off arm)
        for s in (svc_on, svc_off):
            names = set(s.obs_registry.names())
            assert {"retpu_repl_merge_cells", "retpu_repl_early_acks",
                    "retpu_repl_merge_coalesce_ratio"} <= names
        _assert_lanes_equal(svc_on, srvs_on)
        _assert_lanes_equal(svc_off, srvs_off)
    finally:
        _stop(svc_on, srvs_on)
        _stop(svc_off, srvs_off)


def test_wire_coalescing_and_early_ack(tmp_path):
    """A hot-slot storm of SEPARATE scalar kmodifys queued into one
    flush ships fewer merge cells than committed ops (the wire-level
    coalescing the bench meters) and settles through early acks on
    every replica — pure-merge frames ack after the WAL sync, before
    the device scatter."""
    svc, srvs = _group(tmp_path)
    try:
        # warm round: elections ship full-plane; the storm must not
        _settle(svc, [svc.kmodify(e, "warm", funref.ref("rmw:add", 1),
                                  0) for e in range(N_ENS)])
        for _ in range(3):
            futs = [svc.kmodify(0, "hot", funref.ref("rmw:add", 5), 0)
                    for _ in range(8)]
            futs += [svc.kmodify(1, "hot2",
                                 funref.ref("rmw:sub", 2), 0)
                     for _ in range(4)]
            _settle(svc, futs)
            assert all(f.value[0] == "ok" for f in futs)
        g = svc.stats()["group"]
        assert g["repl_merge_entries"] > 0, g
        # contended ops collapsed: N same-slot ops -> ONE cell
        assert g["repl_merge_cells"] < g["repl_merge_ops"], g
        assert g["repl_merge_coalesce_ratio"] > 1.0, g
        assert g["repl_early_acks"] > 0, g
        for s in srvs:
            assert s.core.early_acks > 0, \
                "replica never took the early-ack path"
        r = _settle(svc, [svc.kget(0, "hot"), svc.kget(1, "hot2")])
        assert _counter_val(r[0]) == 3 * 8 * 5
        assert _counter_val(r[1]) == funref.i32(3 * 4 * -2)
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


# -- kmodify_many enqueue-side coalescing ------------------------------------


def _plain_svc(tmp_path, name, comm=True):
    svc = BatchedEnsembleService(WallRuntime(), N_ENS, 1, N_SLOTS,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / name),
                                 tick=None)
    svc._comm_repl = comm
    return svc


def _drive(svc, futs, flushes=40):
    for _ in range(flushes):
        if all(f.done for f in futs):
            break
        svc.flush()
    assert all(f.done for f in futs)
    return [f.value for f in futs]


def test_kmodify_many_enqueue_coalescing_equivalence(tmp_path):
    """Duplicate commutative keys in one kmodify_many fold into a
    single device row: same final values as the un-coalesced arm,
    all members acked with the row's shared version, and that
    version is CAS-usable — while ordered funs never coalesce."""
    a = _plain_svc(tmp_path, "a", comm=True)
    b = _plain_svc(tmp_path, "b", comm=False)
    try:
        keys = ["x", "y", "x", "x"]
        fa = a.kmodify_many(0, keys, funref.ref("rmw:sub", 3), 0)
        fb = b.kmodify_many(0, keys, funref.ref("rmw:sub", 3), 0)
        _drive(a, [fa])
        _drive(b, [fb])
        assert [r[0] for r in fa.value] == ["ok"] * 4
        assert [r[0] for r in fb.value] == ["ok"] * 4
        # two duplicate "x" ops absorbed on the comm arm only
        assert a.rmw_enqueue_coalesced == 2
        assert b.rmw_enqueue_coalesced == 0
        # fastpath counts OPS on both arms (the meter stays honest)
        assert a.rmw_device_fastpath == 4
        assert b.rmw_device_fastpath == 4
        # all members of the coalesced group share the row's version
        vx = [tuple(r[1]) for r, k in zip(fa.value, keys) if k == "x"]
        assert len(set(vx)) == 1
        # final values identical across arms (int32-exact fold)
        for svc, who in ((a, "comm"), (b, "plain")):
            rx = _drive(svc, [svc.kget(0, "x")])[0]
            ry = _drive(svc, [svc.kget(0, "y")])[0]
            assert _counter_val(rx) == funref.i32(-9), who
            assert _counter_val(ry) == funref.i32(-3), who
        # the shared version is the slot's CURRENT version: a CAS
        # against it must succeed (the only token a client could use)
        fc = a.kupdate(0, "x", vx[0], b"swapped")
        _drive(a, [fc])
        assert fc.value[0] == "ok", fc.value
        # ordered funs (set) never coalesce — per-op rows
        coalesced0 = a.rmw_enqueue_coalesced
        fs = a.kmodify_many(0, ["z", "z", "z"],
                            funref.ref("rmw:set", 6), 0)
        _drive(a, [fs])
        assert [r[0] for r in fs.value] == ["ok"] * 3
        assert a.rmw_enqueue_coalesced == coalesced0
        rz = _drive(a, [a.kget(0, "z")])[0]
        assert _counter_val(rz) == 6
    finally:
        a.stop()
        b.stop()


def test_kmodify_many_coalesced_mixed_fresh_and_existing(tmp_path):
    """Coalescing against a slot with committed history: the folded
    group lands on the existing value exactly as the sequenced chain
    would (the merge-vs-chain equivalence the lane is built on)."""
    svc = _plain_svc(tmp_path, "m", comm=True)
    try:
        _drive(svc, [svc.kmodify(0, "c", funref.ref("rmw:add", 100),
                                 0)])
        f = svc.kmodify_many(0, ["c"] * 5, funref.ref("rmw:add", 7), 0)
        _drive(svc, [f])
        assert [r[0] for r in f.value] == ["ok"] * 5
        r = _drive(svc, [svc.kget(0, "c")])[0]
        assert _counter_val(r) == 135
        # semilattice: dup maxes collapse to one idempotent row
        f = svc.kmodify_many(0, ["c", "c"], funref.ref("rmw:max", 999),
                             0)
        _drive(svc, [f])
        r = _drive(svc, [svc.kget(0, "c")])[0]
        assert _counter_val(r) == 999
    finally:
        svc.stop()


# -- ServiceClient idempotency pin -------------------------------------------


def test_client_kmodify_never_silently_retried():
    """kmodify/kmodify_many are NOT in the idempotent-retry set (a
    read-modify-WRITE retried after an ambiguous drop double-applies
    — §18 early acks make RMW storms the hot ambiguous-drop shape),
    and a kmodify dropped mid-ack surfaces DISCONNECTED with the
    request dispatched exactly ONCE."""
    ops = svcnode.ServiceClient.IDEMPOTENT_OPS
    assert "kmodify" not in ops
    assert "kmodify_many" not in ops
    # the whole set stays write-free: only read/introspection verbs
    assert ops <= {"kget", "kget_vsn", "kget_many", "kget_slab",
                   "stats", "health", "metrics"}

    async def scenario():
        seen = []

        async def drop_mid_ack(reader, writer):
            # read ONE request, then die without answering — the
            # op may or may not have applied server-side (ambiguous)
            try:
                head = await reader.readexactly(4)
                (length,) = struct.unpack(">I", head)
                frame = await reader.readexactly(length)
                seen.append(wire.decode(frame)[1])
            except asyncio.IncompleteReadError:
                pass
            writer.close()

        server = await asyncio.start_server(drop_mid_ack,
                                            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        c = svcnode.ServiceClient("127.0.0.1", port)
        await c.connect()
        model = CounterModel("ctr")
        r = await c.kmodify(0, "ctr", funref.ref("rmw:add", 5), 0,
                            timeout=10.0)
        assert r == c.DISCONNECTED, r
        model.unknown(5)  # applied at most once — never twice
        await asyncio.sleep(0.1)
        assert seen == ["kmodify"], \
            f"ambiguous kmodify was re-dispatched: {seen}"
        await c.close()
        server.close()
        await server.wait_closed()
        # both outcomes of the ambiguous op are plausible finals —
        # a silent retry (final 10) would be neither
        model.check_final(0)
        model.check_final(5)
        with pytest.raises(Exception):
            model.check_final(10)

    asyncio.run(scenario())


# -- deterministic convergence (tier-1) --------------------------------------


def test_comm_convergence_mixed_traffic_oneway_drop(tmp_path):
    """Mixed commutative+ordered traffic with a one-way link
    blackhole mid-stream (the PR 9 nemesis shape, deterministic):
    every acked operand lands exactly once — counter finals equal
    the acked-op sums — and the healed replica lanes converge
    bit-equal to the leader's."""
    svc, srvs = _group(tmp_path)
    plan = faults.install(faults.FaultPlan())
    ctrs = {e: CounterModel(f"{e}/cnt") for e in range(N_ENS)}
    keymods = {e: KeyModel(f"{e}/kv") for e in range(N_ENS)}
    try:
        def storm(round_no):
            futs = []
            for e in range(N_ENS):
                opd = 3 + 2 * e + round_no
                futs.append((ctrs[e], opd,
                             svc.kmodify(e, "cnt",
                                         funref.ref("rmw:add", opd),
                                         0)))
                if round_no < 2:
                    # host-payload puts ride the HEALTHY rounds: the
                    # re-sync patch restores their values/keys but
                    # handle numbering is lane-local across a patch,
                    # so the bit-equality canon below sticks to
                    # inline counters through the nemesis window
                    m = keymods[e]
                    v = b"r%d" % round_no
                    op = m.invoke_write(v)
                    futs.append((m, (op, v),
                                 svc.kput(e, "kv", v)))
            _settle(svc, [f for *_x, f in futs], budget=40.0)
            for m, tag, f in futs:
                if isinstance(m, CounterModel):
                    if isinstance(f.value, tuple) \
                            and f.value[0] == "ok":
                        m.ack(tag)
                    else:
                        m.unknown(tag)
                else:
                    op, _v = tag
                    if isinstance(f.value, tuple) \
                            and f.value[0] == "ok":
                        m.ack_write(op)
                    else:
                        m.timeout_write(op)

        storm(0)
        storm(1)
        # one-way blackhole: requests toward replica 0 die; the
        # leader + replica 1 quorum keeps committing
        plan.drop(faults.LOCAL, svc._links[0].label)
        storm(2)
        storm(3)
        plan.heal()
        storm(4)
        # ordered traffic interleaved on the same ensembles
        _settle(svc, [svc.kmodify(e, "cnt2",
                                  funref.ref("rmw:bxor", e + 1), 0)
                      for e in range(N_ENS)])
        _assert_lanes_equal(svc, srvs)
        finals = _settle(svc, [svc.kget(e, "cnt")
                               for e in range(N_ENS)])
        for e in range(N_ENS):
            ctrs[e].check_final(_counter_val(finals[e]))
            assert ctrs[e].n_acked > 0, "storm never acked anything"
        reads = _settle(svc, [svc.kget(e, "kv") for e in range(N_ENS)])
        for e in range(N_ENS):
            assert reads[e][0] == "ok"
            keymods[e].ack_read(reads[e][1])
    finally:
        faults.clear()
        _stop(svc, srvs)


# -- randomized convergence sweep (slow lane) --------------------------------


@pytest.mark.slow
def test_comm_randomized_convergence_crash_and_handoff(tmp_path):
    """THE §18 acceptance sweep on a live 3-host group: randomized
    commutative+ordered load under drop/RTT churn, replica r1 killed
    at the replica_apply_pre_ack barrier (its WAL holds applies past
    its last ack — the retransmit discipline must absorb them, not
    double-merge), restarted, re-synced, and finally carrying the
    quorum ALONE after r2 dies.  CounterModel holds the obligation:
    every final equals the acked-operand sum plus some subset of the
    ambiguous ops — a double-applied merge overshoots, an early-ack
    loss undershoots.  CAS tokens minted after the handoff must
    still swap."""
    import signal

    from test_repgroup import (_make_leader, _restart, _spawn_replica,
                               _wait_synced)

    rng = np.random.default_rng(20818)
    procs, dirs = {}, {}
    os.environ["RETPU_CRASHPOINT"] = "replica_apply_pre_ack:4"
    try:
        dirs["r1"] = str(tmp_path / "r1")
        procs["r1"] = _spawn_replica(dirs["r1"])
    finally:
        os.environ.pop("RETPU_CRASHPOINT", None)
    dirs["r2"] = str(tmp_path / "r2")
    procs["r2"] = _spawn_replica(dirs["r2"])
    svc = _make_leader(tmp_path, [procs["r1"][1], procs["r2"][1]],
                       ack_timeout=5.0)
    plan = faults.install(faults.FaultPlan(seed=20818))
    labels = [l.label for l in svc._links]
    ctrs = {(e, k): CounterModel(f"{e}/c{k}")
            for e in range(4) for k in range(2)}
    keymods = {e: KeyModel(f"{e}/ord") for e in range(4)}

    def settle(futs, budget=45.0):
        end = time.monotonic() + budget
        while not all(f.done for f in futs) \
                and time.monotonic() < end:
            svc.flush()
            time.sleep(0.005)
        assert all(f.done for f in futs), "futures never settled"

    def classify(pending):
        for m, tag, f in pending:
            ok = isinstance(f.value, tuple) and f.value[0] == "ok"
            if isinstance(m, CounterModel):
                m.ack(tag) if ok else m.unknown(tag)
            else:
                m.ack_write(tag) if ok else m.timeout_write(tag)

    restarted = False
    try:
        for rnd in range(10):
            # bounded nemesis: churn only on two rounds (ambiguity
            # must stay rare — the reachable-sum set is 2^n)
            if rnd in (2, 6):
                lab = labels[int(rng.integers(len(labels)))]
                if rng.random() < 0.5:
                    plan.drop(faults.LOCAL, lab)
                else:
                    plan.drop(lab, faults.LOCAL)
            elif rnd in (3, 7):
                plan.set_rtt(faults.LOCAL,
                             labels[int(rng.integers(len(labels)))],
                             float(rng.uniform(1.0, 3.0)))
            else:
                plan.heal()
            pending = []
            for _ in range(8):
                e = int(rng.integers(4))
                r = rng.random()
                if r < 0.7:
                    k = int(rng.integers(2))
                    opd = int(rng.integers(-50, 50))
                    name = "rmw:add" if rng.random() < 0.7 \
                        else "rmw:sub"
                    # retries=1: an internal retry of a quorum-
                    # failed round could re-land an operand that DID
                    # enter the replicated stream — the model's
                    # applied-at-most-once premise needs one attempt
                    fut = svc.kmodify(e, f"c{k}",
                                      funref.ref(name, abs(opd)), 0,
                                      retries=1)
                    signed = abs(opd) if name == "rmw:add" \
                        else -abs(opd)
                    pending.append((ctrs[(e, k)], signed, fut))
                else:
                    m = keymods[e]
                    v = b"o%d-%d" % (rnd, int(rng.integers(1000)))
                    op = m.invoke_write(v)
                    pending.append((m, op, svc.kput(e, "ord", v)))
            settle([f for *_x, f in pending])
            classify(pending)
            if not restarted and procs["r1"][0].poll() is not None:
                # the crashpoint fired mid-stream: bring r1 back on
                # its own ports/data and let the leader re-sync it
                assert procs["r1"][0].poll() == faults.CRASH_EXIT
                plan.heal()
                _restart(procs, dirs, "r1")
                _wait_synced(svc, 2)
                restarted = True
        plan.heal()
        if not restarted:
            # drive applies until the barrier fires (heartbeats are
            # empty applies), then recover the host
            end = time.monotonic() + 90.0
            while procs["r1"][0].poll() is None \
                    and time.monotonic() < end:
                svc.heartbeat()
                time.sleep(0.05)
            assert procs["r1"][0].poll() == faults.CRASH_EXIT, \
                "replica never died at replica_apply_pre_ack"
            _restart(procs, dirs, "r1")
            _wait_synced(svc, 2)
        # handoff: the once-crashed host carries the quorum alone
        p2, _, _ = procs["r2"]
        p2.send_signal(signal.SIGKILL)
        p2.wait()
        # post-handoff traffic still commits (r1's lane must hold
        # every early-acked merge it WAL-ed before the crash)
        post = []
        for (e, k), m in ctrs.items():
            fut = svc.kmodify(e, f"c{k}", funref.ref("rmw:add", 11),
                              0, retries=1)
            post.append((m, 11, fut))
        settle([f for *_x, f in post], budget=60.0)
        classify(post)
        finals = [svc.kget(e, f"c{k}") for (e, k) in ctrs]
        settle(finals, budget=60.0)
        for ((e, k), m), f in zip(ctrs.items(), finals):
            m.check_final(_counter_val(f.value))
        assert sum(m.n_acked for m in ctrs.values()) > 20
        # ordered keys: plausible per the KeyModel across the sweep
        reads = [svc.kget(e, "ord") for e in range(4)]
        settle(reads, budget=60.0)
        for e, f in zip(range(4), reads):
            if isinstance(f.value, tuple) and f.value[0] == "ok":
                keymods[e].ack_read(f.value[1])
        # CAS tokens minted through the comm lane survive the
        # handoff: read-version -> swap must succeed
        gv = svc.kget_vsn(0, "c0")
        settle([gv], budget=30.0)
        assert gv.value[0] == "ok"
        cu = svc.kupdate(0, "c0", tuple(gv.value[2]), b"swapped")
        settle([cu], budget=30.0)
        assert cu.value[0] == "ok", cu.value
    finally:
        faults.clear()
        try:
            svc.stop()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
