"""Active-column compaction: the packed d2h payload gathers down to
the live working set (O(K·|A|), not O(K·E)) while staying a PURE
re-indexing — every result a full-width pack would deliver arrives
bit-identically.  These tests pin:

- the pack/unpack layout round trip through an active index list
  (pow2 padding included),
- the skew-load equivalence sweep the issue demands: one hot ensemble
  at full depth + hundreds of idle/1-deep columns, seeded op mix
  including OP_RMW and wide groups, compacted results element-equal
  to a full-width-pack reference service,
- corruption detected inside a heavily-compacted launch still reaches
  the exchange/scrub path (the corrupt mask stays full width),
- a replication-group replica applies a compacted leader stream
  across an active-set change between flushes (CRC + state equality,
  even with the two sides in DIFFERENT pack layouts),
- the (K, A) warmup grid, and
- WAL compaction deferred off the hot path (idle-flush scheduling,
  the hard 2x in-line bound, and the svc_compaction marks).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu import funref  # noqa: E402
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel import batched_host as bh  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime,
)


def make_pair(n_ens, n_peers, n_slots, k):
    """(compacted service, full-width reference service) — identical
    but for the pack layout."""
    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                 n_slots, tick=None,
                                 max_ops_per_tick=k)
    ref = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                 n_slots, tick=None,
                                 max_ops_per_tick=k)
    assert svc._compact  # default on
    ref._compact = False
    return svc, ref


def assert_engine_equal(a, b):
    for f in eng.EngineState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a.state, f)),
            np.asarray(getattr(b.state, f)), err_msg=f)


# -- layout round trip -------------------------------------------------------


def _random_result(rng, k, e, m, cols):
    """KvResult planes with client data only in the active columns
    (exactly what a real launch produces: inactive columns carry the
    all-false/zero NOOP results) but FULL-width quorum/corrupt/won
    planes."""
    def bplane():
        full = np.zeros((k, e), bool)
        full[:, cols] = rng.random((k, len(cols))) < 0.5
        return full

    value = np.zeros((k, e), np.int32)
    value[:, cols] = rng.integers(0, 1 << 20, (k, len(cols)))
    vsn = np.zeros((k, e, 2), np.int32)
    vsn[:, cols] = rng.integers(0, 100, (k, len(cols), 2))
    res = eng.KvResult(
        committed=jnp.asarray(bplane()), get_ok=jnp.asarray(bplane()),
        found=jnp.asarray(bplane()), value=jnp.asarray(value),
        obj_vsn=jnp.asarray(vsn),
        quorum_ok=jnp.asarray(rng.random((k, e)) < 0.5),
        tree_corrupt=jnp.asarray(rng.random((k, e, m)) < 0.1))
    won = jnp.asarray(rng.random((e,)) < 0.5)
    return won, res


@pytest.mark.parametrize("cols,a_width", [
    ([2, 7, 8, 21], 4),       # exact pow2 fit
    ([0, 3, 9, 20, 30], 8),   # padded bucket (pad repeats index 0)
    ([31], 1),                # single hot column
])
def test_pack_unpack_roundtrip_active(cols, a_width):
    rng = np.random.default_rng(7)
    k, e, m = 5, 32, 3
    cols = np.asarray(cols, np.int32)
    won, res = _random_result(rng, k, e, m, cols)

    full_flat = np.asarray(bh._pack_results(won, res, True))
    pad = np.zeros((a_width,), np.int32)
    pad[:len(cols)] = cols
    comp_flat = np.asarray(
        bh._pack_results(won, res, True, active_idx=jnp.asarray(pad)))
    assert comp_flat.nbytes < full_flat.nbytes
    assert comp_flat.nbytes == bh.packed_nbytes(e, m, k, True, a_width)

    o_full = bh.unpack_results(full_flat, e, m, k, True)
    o_comp = bh.unpack_results(comp_flat, e, m, k, True,
                               active=cols, a_width=a_width)
    for name, a, b in zip(("won", "quorum", "corrupt", "committed",
                           "get_ok", "found", "value", "vsn"),
                          o_full, o_comp):
        np.testing.assert_array_equal(a, b, err_msg=name)


# -- the skew-load equivalence sweep ----------------------------------------

E_SWEEP = 512
K_SWEEP = 64


def _skew_planes(rng, n_ens, n_slots, k, distinct=False):
    """Seeded skewed op planes: column 0 hot at full depth k (mix of
    PUT / GET / CAS / RMW / tombstone-PUT), roughly a third of the
    other columns 1-deep, a few 2-3 deep, the rest idle.  With
    ``distinct``, slots within a column never repeat (the wide
    scheduler then packs G <= 2 groups)."""
    kind = np.zeros((k, n_ens), np.int32)
    slot = np.zeros((k, n_ens), np.int32)
    val = np.zeros((k, n_ens), np.int32)
    exp_e = np.zeros((k, n_ens), np.int32)
    exp_s = np.zeros((k, n_ens), np.int32)

    def fill(col, depth):
        kinds = rng.choice(
            [eng.OP_PUT, eng.OP_GET, eng.OP_CAS, eng.OP_RMW,
             eng.OP_PUT], depth, p=[0.35, 0.25, 0.15, 0.15, 0.1])
        kind[:depth, col] = kinds
        if distinct:
            slot[:depth, col] = rng.permutation(n_slots)[:depth]
        else:
            slot[:depth, col] = rng.integers(0, n_slots, depth)
        val[:depth, col] = rng.integers(1, 1 << 20, depth)
        tomb = (kinds == eng.OP_PUT) & (rng.random(depth) < 0.2)
        val[:depth, col][tomb] = 0
        rmw = kinds == eng.OP_RMW
        exp_e[:depth, col][rmw] = rng.choice(
            [eng.RMW_ADD, eng.RMW_MAX, eng.RMW_BXOR], int(rmw.sum()))
        # CAS rows: create-if-missing on the first pass; later
        # passes feed real versions from the caller

    fill(0, k)
    # most of the grid idles: the hot column plus ~E/8 light columns
    # (bucketed active set well below E/4, so the payload cut is >4x)
    light = rng.permutation(np.arange(1, n_ens))[:n_ens // 8 - 1]
    for col in light[:-4]:
        fill(int(col), 1)
    for col in light[-4:]:  # a few middle-depth columns
        fill(int(col), int(rng.integers(2, 4)))
    return kind, slot, val, exp_e, exp_s


def test_skew_equivalence_sweep():
    """1 hot ensemble at depth 64 + ~60 one-to-three-deep + ~450 idle
    of 512: the compacted service's result planes are identical to
    the full-width reference over repeated seeded sweeps (versions
    advance, CAS rows start hitting committed state), while the d2h
    payload shrinks by > 4x."""
    svc, ref = make_pair(E_SWEEP, 3, 64, K_SWEEP)
    rng = np.random.default_rng(11)
    planes = [_skew_planes(np.random.default_rng(s), E_SWEEP, 64,
                           K_SWEEP) for s in rng.integers(0, 999, 3)]
    for i, (kind, slot, val, exp_e, exp_s) in enumerate(planes):
        out_c = svc.execute(kind, slot, val, exp_epoch=exp_e,
                            exp_seq=exp_s)
        out_f = ref.execute(kind, slot, val, exp_epoch=exp_e,
                            exp_seq=exp_s)
        for name, a, b in zip(("committed", "get_ok", "found",
                               "value"), out_c, out_f):
            np.testing.assert_array_equal(a, b, err_msg=name)
        if i == 0:
            # the first launch elects ALL E columns (its active set
            # is genuinely full width); the payload claim below is
            # about steady state, so count from the second launch
            for s in (svc, ref):
                s.payload_bytes = 0
                s.payload_bytes_full_width = 0
                s._occ_sum = 0.0
                s._occ_launches = 0
    assert_engine_equal(svc, ref)
    # the mix really exercised the op kinds
    kind = planes[0][0]
    assert all((kind == op).any() for op in
               (eng.OP_PUT, eng.OP_GET, eng.OP_CAS, eng.OP_RMW))
    # and the payload shrank: this is the whole point
    assert svc.payload_bytes < ref.payload_bytes / 4, (
        svc.payload_bytes, ref.payload_bytes)
    assert svc.stats()["grid_occupancy"] <= 0.25
    assert ref.stats()["grid_occupancy"] == 1.0


def test_skew_equivalence_wide_groups():
    """The same sweep through the WIDE scheduler (distinct-slot
    planes, both arms RETPU_WIDE semantics): compacted wide results
    — the sliced [G, A, W] launch routed back through the plan — stay
    element-identical to the full-width wide reference.  E = 256 so
    the launch really slices (SLICE_MIN_E)."""
    n_ens, n_slots, k = 256, 32, 16
    svc, ref = make_pair(n_ens, 3, n_slots, k)
    svc._wide = ref._wide = True
    rng = np.random.default_rng(23)
    for i, s in enumerate(rng.integers(0, 999, 2)):
        kind, slot, val, exp_e, exp_s = _skew_planes(
            np.random.default_rng(s), n_ens, n_slots, k,
            distinct=True)
        out_c = svc.execute(kind, slot, val, exp_epoch=exp_e,
                            exp_seq=exp_s)
        out_f = ref.execute(kind, slot, val, exp_epoch=exp_e,
                            exp_seq=exp_s)
        for name, a, b in zip(("committed", "get_ok", "found",
                               "value"), out_c, out_f):
            np.testing.assert_array_equal(a, b, err_msg=name)
        if i == 0:  # first launch = all-columns election, full width
            for sv in (svc, ref):
                sv.payload_bytes = 0
                sv.payload_bytes_full_width = 0
    assert svc.wide_launches > 0 and ref.wide_launches > 0
    assert_engine_equal(svc, ref)
    assert svc.payload_bytes < ref.payload_bytes / 4


def test_keyed_equivalence_with_rmw():
    """The queued keyed path (futures, want_vsn results, the kmodify
    device fast path) resolves identically on a compacted and a
    full-width service — versions included."""
    svc, ref = make_pair(64, 3, 16, 8)
    results = []
    for s in (svc, ref):
        # elect every ensemble first (an election-only launch is
        # full width by design); the payload claim is steady-state
        w = [s.kput(e_, "warm", 1) for e_ in range(s.n_ens)]
        while any(s.queues):
            s.flush()
        assert all(f.value[0] == "ok" for f in w)
        s.payload_bytes = 0
        s.payload_bytes_full_width = 0
        futs = []
        for i in range(8):
            futs.append(s.kput(0, f"k{i}", 1000 + i))
        futs.append(s.kput(9, "x", 7))
        futs.append(s.kmodify(17, "ctr", funref.ref("rmw:add", 5), 0))
        futs.append(s.kmodify(17, "ctr", funref.ref("rmw:add", 5), 0))
        futs.append(s.kget_vsn(9, "x"))
        while any(s.queues):
            s.flush()
        # second wave: a DIFFERENT active set (ensembles 3, 17, 40)
        futs.append(s.kput(3, "y", 1))
        futs.append(s.kget(17, "ctr"))
        futs.append(s.kdelete(40, "nope"))
        while any(s.queues):
            s.flush()
        results.append([f.value for f in futs])
    assert results[0] == results[1]
    assert svc.rmw_device_fastpath > 0
    assert_engine_equal(svc, ref)
    assert svc.payload_bytes < ref.payload_bytes / 2


def test_corrupt_flag_reaches_scrub_under_compaction():
    """The corrupt mask stays FULL width: a launch compacted down to
    one active column still reports the integrity-gate failure and
    triggers the same exchange/repair a full-width pack would."""
    svc, ref = make_pair(32, 3, 8, 4)
    for s in (svc, ref):
        assert_done = []
        f = s.kput(5, "k", 42)
        while any(s.queues):
            s.flush()
        assert f.value[0] == "ok"
        # damage replica 1's leaf for ensemble 5's slot on device
        slot = s.key_slot[5]["k"]
        leaf = np.asarray(s.state.tree_leaf).copy()
        leaf[5, 1, slot] ^= 0xDEAD
        s.state = s.state._replace(tree_leaf=jnp.asarray(leaf))
        # leased fast reads never touch the device — expire the
        # leases so this read takes the (compacted) round and
        # exercises the full-width corrupt mask under test
        s.lease_until[:] = 0.0
        g = s.kget(5, "k")  # active set = {5}: maximally compacted
        while any(s.queues):
            s.flush()
        assert_done.append(g.value)
        assert g.value == ("ok", 42)
        assert s.corruptions >= 1
    assert svc.corruptions == ref.corruptions
    assert_engine_equal(svc, ref)  # exchange healed both identically
    assert svc.payload_bytes < ref.payload_bytes


# -- replication: compacted leader stream ------------------------------------


def test_repgroup_replica_applies_compacted_stream():
    """A replica lane applies a COMPACTED leader's flush stream —
    with the active set changing between flushes — and lands on the
    bit-identical state and ack CRCs, even though the replica itself
    runs the FULL-WIDTH pack layout (the layout is host-local; the
    frames ship op planes, not packed results)."""
    n_ens, n_slots, k = 16, 8, 4
    leader = BatchedEnsembleService(WallRuntime(), n_ens, 1, n_slots,
                                    tick=None, max_ops_per_tick=k)
    rsvc = BatchedEnsembleService(WallRuntime(), n_ens, 1, n_slots,
                                  tick=None, max_ops_per_tick=k)
    rsvc._compact = False  # cross-layout: leader compacts, lane not
    core = repgroup.ReplicaCore(rsvc)
    assert core.handle_promise(1)[1] is True

    frames = []
    crcs = []
    orig_enq = leader._launch_enqueue
    orig_res = leader._launch_resolve

    def spy_enqueue(kind, slot, val, k_, want_vsn, exp_e=None,
                    exp_s=None, entries=None, elect=None, cand=None,
                    lease_ok=None):
        if elect is None:
            elect, cand = leader._election_inputs()
        if lease_ok is None:
            lease_ok = leader.lease_until > leader.runtime.now
        meta = repgroup._entries_meta(entries, kind, slot,
                                      leader.values)
        frames.append(repgroup.build_apply_frame(
            1, len(frames) + 1, k_, want_vsn, elect, lease_ok,
            np.asarray(kind), np.asarray(slot), np.asarray(val),
            exp_e, exp_s, meta))
        return orig_enq(kind, slot, val, k_, want_vsn, exp_e, exp_s,
                        entries, elect, cand, lease_ok)

    def spy_resolve(fl, wait_key="device_d2h"):
        out = orig_res(fl, wait_key)
        crcs.append(repgroup.result_crc(out[0], out[4]))
        return out

    leader._launch_enqueue = spy_enqueue
    leader._launch_resolve = spy_resolve

    # flush 1: active set {0, 2} (put + device RMW)
    f1 = [leader.kput(0, "a", 11),
          leader.kmodify(2, "ctr", funref.ref("rmw:add", 3), 0)]
    while any(leader.queues):
        leader.flush()
    # flush 2: active set changes to {1, 3}
    f2 = [leader.kput(1, "b", 22), leader.kput(3, "c", 33)]
    while any(leader.queues):
        leader.flush()
    # flush 3: back to {0} with a read + overwrite
    f3 = [leader.kget(0, "a"), leader.kput(0, "a", 44)]
    while any(leader.queues):
        leader.flush()
    assert all(f.done for f in f1 + f2 + f3)
    assert f3[0].value == ("ok", 11)
    assert leader.payload_bytes < leader.payload_bytes_full_width

    for i, frame in enumerate(frames):
        ack = core.handle_apply(frame)
        assert ack[0] == "applied", ack
        assert ack[3] == crcs[i], f"CRC diverged on frame {i}"
    assert_engine_equal(leader, rsvc)
    for e in range(n_ens):
        assert leader.key_slot[e] == rsvc.key_slot[e], e
    # the committed RMW slot is device-native on BOTH lanes
    assert rsvc._inline_slots[2] == leader._inline_slots[2] != set()


# -- (K, A) warmup grid ------------------------------------------------------


def test_warmup_covers_ka_grid():
    svc = BatchedEnsembleService(WallRuntime(), 64, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    assert svc._a_ladder() == [None, 8, 16, 32]
    svc.warmup()  # full (K, A) grid; must not raise or touch state
    assert svc.flushes == 0 and not np.asarray(svc.state.obj_seq).any()
    # restricted bucket list (the bench/svcnode sharing surface)
    svc.warmup(buckets=[(4, 8), (4, None), (1, 8)])
    f = svc.kput(3, "k", 1)
    while any(svc.queues):
        svc.flush()
    assert f.value[0] == "ok"


def test_a_ladder_off_when_disabled():
    svc = BatchedEnsembleService(WallRuntime(), 16, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    svc._compact = False
    assert svc._a_ladder() == [None]
    svc.warmup()
    f = svc.kput(0, "k", 1)
    while any(svc.queues):
        svc.flush()
    assert f.value[0] == "ok"
    assert svc.stats()["grid_occupancy"] == 1.0


# -- WAL compaction off the hot path ----------------------------------------


def test_wal_compaction_deferred_to_idle_flush(tmp_path):
    """Under sustained load (queues never empty across a flush) the
    record bound does NOT trigger an in-line save(); the compaction
    runs on the first idle flush, with svc_compaction marks in
    stats() and the latency records."""
    svc = BatchedEnsembleService(
        WallRuntime(), 2, 1, 16, tick=None, max_ops_per_tick=2,
        data_dir=str(tmp_path), wal_compact_records=4)
    futs = [svc.kput(0, f"k{i}", i + 1) for i in range(10)]
    while any(svc.queues):
        before = svc.wal_compactions
        svc.flush()
        if any(svc.queues):
            # busy flush (work still queued): compaction must wait —
            # the old behavior saved synchronously right here
            assert svc.wal_compactions == before, \
                "compaction ran on the hot path"
    assert all(f.value[0] == "ok" for f in futs)
    # queues drained inside the last flush call -> it was idle at
    # maintenance time and past the bound, so compaction ran there
    assert svc.wal_compactions == 1
    st = svc.stats()["svc_compaction"]
    assert st["count"] == 1 and st["last_ms"] > 0
    lb = svc.latency_breakdown()
    assert lb["svc_compaction"]["p99_ms"] > 0  # visible, not averaged
    assert lb["svc_compaction"]["p50_ms"] > 0  # into launch records
    assert svc._wal.count == 0  # rotated into the checkpoint
    svc.stop()


def test_wal_compaction_hard_bound_inline(tmp_path):
    """Past the hard 2x record bound the compaction runs IN-LINE even
    while loaded — unbounded WAL growth (and restart replay time)
    must stay bounded."""
    svc = BatchedEnsembleService(
        WallRuntime(), 2, 1, 32, tick=None, max_ops_per_tick=2,
        data_dir=str(tmp_path), wal_compact_records=3)
    seen = []
    orig = svc._compact_wal
    svc._compact_wal = lambda idle: (seen.append(idle), orig(idle))
    futs = [svc.kput(0, f"k{i}", i + 1) for i in range(20)]
    while any(svc.queues):
        svc.flush()
    assert all(f.done for f in futs)
    # the first compaction fired through the 2x bound while LOADED
    # (not the idle path; save()'s own drain then emptied the queues)
    assert seen and seen[0] is False, seen
    assert svc.wal_compactions >= 1
    assert svc._wal.count <= 2 * svc.wal_compact_records
    svc.stop()


def test_restore_after_deferred_compaction(tmp_path):
    """The deferred compaction still subsumes the WAL correctly: a
    restore after idle-flush compaction sees every acked write."""
    svc = BatchedEnsembleService(
        WallRuntime(), 2, 1, 16, tick=None, max_ops_per_tick=4,
        data_dir=str(tmp_path), wal_compact_records=3)
    futs = [svc.kput(0, f"k{i}", bytes([i])) for i in range(6)]
    while any(svc.queues):
        svc.flush()
    assert all(f.value[0] == "ok" for f in futs)
    assert svc.wal_compactions >= 1
    svc.stop()
    svc2 = BatchedEnsembleService.restore(
        WallRuntime(), str(tmp_path), tick=None,
        data_dir=str(tmp_path))
    gets = [svc2.kget(0, f"k{i}") for i in range(6)]
    while any(svc2.queues):
        svc2.flush()
    assert [g.value for g in gets] == [("ok", bytes([i]))
                                       for i in range(6)]
    svc2.stop()
