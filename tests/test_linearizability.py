"""sc.erl analog: randomized concurrent K/V workloads with peer
freezes and network partitions; plausible-value + no-data-loss
postconditions (test/sc.erl get_post:112-148, prop_sc:835-880).
"""

import pytest

from riak_ensemble_tpu.linearizability import KeyModel, Violation, Workload
from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import NOTFOUND, PeerId


# -- model unit tests -------------------------------------------------------


def test_model_accepts_acked_write_read():
    m = KeyModel("k")
    op = m.invoke_write(b"a")
    m.ack_write(op)
    m.ack_read(b"a")


def test_model_rejects_stale_read():
    m = KeyModel("k")
    op = m.invoke_write(b"a")
    m.ack_write(op)
    op2 = m.invoke_write(b"b")
    m.ack_write(op2)
    with pytest.raises(Violation):
        m.ack_read(b"a")  # superseded by acked b


def test_model_rejects_lost_write():
    m = KeyModel("k")
    op = m.invoke_write(b"a")
    m.ack_write(op)
    with pytest.raises(Violation):
        m.ack_read(NOTFOUND)  # data loss


def test_model_concurrent_write_may_win():
    m = KeyModel("k")
    op1 = m.invoke_write(b"a")
    op2 = m.invoke_write(b"b")  # concurrent
    m.ack_write(op1)
    m.ack_write(op2)
    m.ack_read(b"b")
    with pytest.raises(Violation):
        m.ack_read(b"a")


def test_model_timeout_write_remains_plausible():
    m = KeyModel("k")
    op1 = m.invoke_write(b"a")
    m.ack_write(op1)
    op2 = m.invoke_write(b"b")
    m.timeout_write(op2)  # unknown outcome
    m.ack_read(b"b")      # it may have landed
    m.ack_read(b"b")
    with pytest.raises(Violation):
        m.ack_read(b"a")  # read pinned the state to b


def test_model_timeout_op_may_land_late():
    """An op with NO response has no linearization upper bound: a
    timed-out delete may apply after a later acked write (e.g. queued
    behind a suspended peer that later re-wins the leadership)."""
    from riak_ensemble_tpu.types import NOTFOUND

    m = KeyModel("k")
    op1 = m.invoke_write(b"a")
    m.ack_write(op1)
    opd = m.invoke_write(NOTFOUND)  # delete
    m.timeout_write(opd)            # client gave up; outcome unknown
    op2 = m.invoke_write(b"b")
    m.ack_write(op2)
    m.ack_read(NOTFOUND)            # late delete landed after b: legal
    # but a value never written is still a violation
    with pytest.raises(Violation):
        m.ack_read(b"never-written")


# -- single-node ensemble under peer freezes --------------------------------


@pytest.mark.parametrize("seed", [101, 102])
def test_workload_single_node_freezes(seed):
    mc = ManagedCluster(seed=seed)
    mc.ens_start(3)
    w = Workload(mc, "root", n_workers=3, n_keys=3, ops_per_worker=40,
                 seed=seed)
    w.run(partitions=False)
    assert sum(w.op_counts.values()) >= 120


# -- multi-node ensemble under partitions (sc.erl partition_nodes) ----------


@pytest.mark.parametrize("seed", [501, 502])
def test_workload_with_membership_churn(seed):
    """replace_members-under-load: concurrent add→remove membership
    cycles through the real update_members path while workers run and
    peers freeze/partition."""
    mc = ManagedCluster(seed=seed, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("sc", peers)
    mc.wait_stable("sc")

    w = Workload(mc, "sc", n_workers=3, n_keys=3, ops_per_worker=30,
                 op_timeout=1.5, seed=seed, nemesis_hold=(0.3, 1.5),
                 member_churn=True)
    w.run(partitions=True)
    assert sum(w.op_counts.values()) >= 90


@pytest.mark.parametrize("seed", [201])
def test_workload_multinode_partitions(seed):
    mc = ManagedCluster(seed=seed, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("sc", peers)
    mc.wait_stable("sc")

    # Short op timeout + long partition holds so some ops genuinely
    # time out with unknown outcome (the hard case for the model).
    w = Workload(mc, "sc", n_workers=3, n_keys=3, ops_per_worker=30,
                 op_timeout=1.0, seed=seed, nemesis_hold=(0.5, 2.5))
    w.run(partitions=True)
    assert sum(w.op_counts.values()) >= 90
    outcomes = {ev[0] for m in w.models.values() for ev in m.history}
    assert "ack" in outcomes and "read" in outcomes
