"""sc.erl analog: randomized concurrent K/V workloads with peer
freezes and network partitions; plausible-value + no-data-loss
postconditions (test/sc.erl get_post:112-148, prop_sc:835-880).
"""

import pytest

from riak_ensemble_tpu.linearizability import KeyModel, Violation, Workload
from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import NOTFOUND, PeerId


# -- model unit tests -------------------------------------------------------


def test_model_accepts_acked_write_read():
    m = KeyModel("k")
    op = m.invoke_write(b"a")
    m.ack_write(op)
    m.ack_read(b"a")


def test_model_rejects_stale_read():
    m = KeyModel("k")
    op = m.invoke_write(b"a")
    m.ack_write(op)
    op2 = m.invoke_write(b"b")
    m.ack_write(op2)
    with pytest.raises(Violation):
        m.ack_read(b"a")  # superseded by acked b


def test_model_rejects_lost_write():
    m = KeyModel("k")
    op = m.invoke_write(b"a")
    m.ack_write(op)
    with pytest.raises(Violation):
        m.ack_read(NOTFOUND)  # data loss


def test_model_concurrent_write_may_win():
    m = KeyModel("k")
    op1 = m.invoke_write(b"a")
    op2 = m.invoke_write(b"b")  # concurrent
    m.ack_write(op1)
    m.ack_write(op2)
    m.ack_read(b"b")
    with pytest.raises(Violation):
        m.ack_read(b"a")


def test_model_timeout_write_remains_plausible():
    m = KeyModel("k")
    op1 = m.invoke_write(b"a")
    m.ack_write(op1)
    op2 = m.invoke_write(b"b")
    m.timeout_write(op2)  # unknown outcome
    m.ack_read(b"b")      # it may have landed
    m.ack_read(b"b")
    with pytest.raises(Violation):
        m.ack_read(b"a")  # read pinned the state to b


def test_model_timeout_op_may_land_late():
    """An op with NO response has no linearization upper bound: a
    timed-out delete may apply after a later acked write (e.g. queued
    behind a suspended peer that later re-wins the leadership)."""
    from riak_ensemble_tpu.types import NOTFOUND

    m = KeyModel("k")
    op1 = m.invoke_write(b"a")
    m.ack_write(op1)
    opd = m.invoke_write(NOTFOUND)  # delete
    m.timeout_write(opd)            # client gave up; outcome unknown
    op2 = m.invoke_write(b"b")
    m.ack_write(op2)
    m.ack_read(NOTFOUND)            # late delete landed after b: legal
    # but a value never written is still a violation
    with pytest.raises(Violation):
        m.ack_read(b"never-written")


# -- single-node ensemble under peer freezes --------------------------------


@pytest.mark.parametrize("seed", [101, 102])
def test_workload_single_node_freezes(seed):
    mc = ManagedCluster(seed=seed)
    mc.ens_start(3)
    w = Workload(mc, "root", n_workers=3, n_keys=3, ops_per_worker=40,
                 seed=seed)
    w.run(partitions=False)
    assert sum(w.op_counts.values()) >= 120


# -- multi-node ensemble under partitions (sc.erl partition_nodes) ----------


@pytest.mark.parametrize("seed", [501, 502])
def test_workload_with_membership_churn(seed):
    """replace_members-under-load: concurrent add→remove membership
    cycles through the real update_members path while workers run and
    peers freeze/partition."""
    mc = ManagedCluster(seed=seed, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("sc", peers)
    mc.wait_stable("sc")

    w = Workload(mc, "sc", n_workers=3, n_keys=3, ops_per_worker=30,
                 op_timeout=1.5, seed=seed, nemesis_hold=(0.3, 1.5),
                 member_churn=True)
    w.run(partitions=True)
    assert sum(w.op_counts.values()) >= 90


@pytest.mark.parametrize("seed", [201])
def test_workload_multinode_partitions(seed):
    mc = ManagedCluster(seed=seed, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("sc", peers)
    mc.wait_stable("sc")

    # Short op timeout + long partition holds so some ops genuinely
    # time out with unknown outcome (the hard case for the model).
    w = Workload(mc, "sc", n_workers=3, n_keys=3, ops_per_worker=30,
                 op_timeout=1.0, seed=seed, nemesis_hold=(0.5, 2.5))
    w.run(partitions=True)
    assert sum(w.op_counts.values()) >= 90
    outcomes = {ev[0] for m in w.models.values() for ev in m.history}
    assert "ack" in outcomes and "read" in outcomes


# -- batched-service read fast path under nemesis ---------------------------
#
# The lease-protected read fast path (batched_host, ARCHITECTURE §9)
# serves linearizable kgets from the leader's committed host mirror —
# no device round — inside a margin-checked lease.  These sweeps drive
# it through ServiceReadWorkload's nemesis schedule: lease expiry
# mid-workload, leader step-down/re-election, and a skewed-margin
# clock; the KeyModel raises Violation on any stale or lost read.


def _read_fastpath_sweep(seed, *, pipeline_depth=1, margin=None,
                         rounds=40):
    pytest.importorskip("jax")
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.linearizability import ServiceReadWorkload
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService,
    )
    from riak_ensemble_tpu.runtime import Runtime

    config = fast_test_config()
    if margin is not None:
        config.read_lease_margin = margin
    runtime = Runtime(seed=seed)
    svc = BatchedEnsembleService(runtime, 4, 5, n_slots=8, tick=None,
                                 max_ops_per_tick=8, config=config,
                                 pipeline_depth=pipeline_depth)
    w = ServiceReadWorkload(svc, runtime, seed=seed, rounds=rounds)
    w.run()  # raises Violation on a stale/lost read
    return svc


@pytest.mark.parametrize("seed", [1201, 1202, 1203])
def test_service_read_fastpath_linearizable(seed):
    svc = _read_fastpath_sweep(seed)
    # the sweep must exercise BOTH sides of the router: mirror-served
    # hits AND device-round fallbacks forced by the nemesis
    assert svc.read_fastpath_hits > 0
    assert svc.read_fastpath_misses > 0
    reasons = svc.read_fastpath_miss_reasons
    assert reasons.get("no_lease", 0) > 0, reasons  # lease/margin races
    assert reasons.get("pending_write", 0) > 0, reasons


@pytest.mark.parametrize("seed", [1301, 1302])
def test_service_read_fastpath_linearizable_pipelined(seed):
    """Same sweep across the depth-2 launch pipeline: an acked write
    must be visible to every later fast read even while its launch's
    resolve runs one flush late (the pending-write index spans the
    in-flight window)."""
    svc = _read_fastpath_sweep(seed, pipeline_depth=2)
    assert svc.read_fastpath_hits > 0
    assert svc.read_fastpath_misses > 0


@pytest.mark.parametrize("seed", [1401])
def test_service_read_fastpath_skewed_margin(seed):
    """A margin close to the whole lease (the skewed-clock model:
    trust almost nothing of the grant) must stay linearizable and
    push traffic onto the fallback round — the fast path degrades to
    correctness, never to staleness."""
    from riak_ensemble_tpu.config import fast_test_config

    cfg = fast_test_config()
    wide_margin = cfg.lease() * 0.9  # still < follower() - lease()
    svc = _read_fastpath_sweep(seed, margin=wide_margin)
    assert svc.read_fastpath_misses > 0
    assert svc.read_fastpath_miss_reasons.get("no_lease", 0) > 0
