"""The unified observability plane (docs/ARCHITECTURE.md §11).

Covers the four pieces end to end: registry/histogram correctness
against a numpy oracle, flight-recorder trigger + ring bound + dump
schema round-trip, leader→replica flush_id correlation on a LIVE
replication group (every replica apply span names a leader flush
span), and per-tenant counter attribution under a two-tenant
workload — plus the satellite contracts (Tracer's bounded finished
ring folding into a registry, the RETPU_OBS=0 short-circuit, and the
svcnode ``metrics`` verb)."""

import json
import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import obs, wire  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.obs.flightrec import DUMP_SCHEMA  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)


# -- registry ---------------------------------------------------------------

def test_histogram_matches_numpy_oracle():
    """Fixed-bucket counts must agree exactly with a searchsorted
    oracle over the same edges, and the quantile estimate must land
    inside the true quantile's bucket."""
    h = obs.Histogram("retpu_test_ms")
    rng = np.random.default_rng(7)
    vals = rng.lognormal(1.0, 1.5, 4000)
    for v in vals:
        h.record(float(v))
    edges = np.asarray(h.buckets)
    oracle = np.bincount(np.searchsorted(edges, vals, side="left"),
                         minlength=len(edges) + 1)
    assert oracle.tolist() == h.counts
    assert h.count == len(vals)
    assert np.isclose(h.sum, vals.sum())
    for q in (0.5, 0.9, 0.99):
        est = h.percentile(q)
        true = float(np.percentile(vals, q * 100))
        i = int(np.searchsorted(edges, true, side="left"))
        lo = 0.0 if i == 0 else float(edges[i - 1])
        hi = float(edges[i]) if i < len(edges) else float("inf")
        assert lo <= est <= min(hi, float(edges[-1])), (q, est, true)


def test_histogram_empty_and_overflow():
    h = obs.Histogram("retpu_test_ms", buckets=(1.0, 10.0))
    assert h.percentile(0.5) == 0.0
    h.record(5000.0)  # overflow bucket
    assert h.counts == [0, 0, 1]
    # the overflow bucket has no honest upper edge: report its floor
    assert h.percentile(0.99) == 10.0


def test_registry_counters_gauges_labels_and_export():
    r = obs.MetricsRegistry()
    c = r.counter("retpu_x_total", "a counter")
    c.inc()
    c.labels("hot").inc(3)
    r.gauge("retpu_g", "a gauge", fn=lambda: 42)
    r.histogram("retpu_h_ms").record(2.0)
    r.collect(lambda: {"retpu_fam": {
        "type": "counter", "help": "fam",
        "values": {"a": 1, "b": 2}}})
    snap = r.snapshot()
    assert snap["retpu_x_total"]["hot"] == 3
    assert snap["retpu_g"] == 42
    assert snap["retpu_h_ms"]["count"] == 1
    assert snap["retpu_fam"] == {"a": 1, "b": 2}
    # the snapshot is wire-encodable (the svcnode metrics verb ships
    # it through the restricted codec)
    assert wire.decode(wire.encode(snap)) == snap
    txt = r.render_prometheus()
    assert '# TYPE retpu_x_total counter' in txt
    assert 'retpu_x_total{tenant="hot"} 3' in txt
    assert 'retpu_h_ms_bucket' in txt and 'retpu_h_ms_count 1' in txt
    assert 'retpu_fam{tenant="a"} 1' in txt
    assert sorted(r.names()) == ["retpu_fam", "retpu_g", "retpu_h_ms",
                                 "retpu_x_total"]
    # the unlabeled sample of a labeled family exports under "" (not
    # a forged tenant named "None")
    assert snap["retpu_x_total"][""] == 1
    assert "None" not in snap["retpu_x_total"]


def test_prometheus_label_escaping():
    """Tenant labels are arbitrary user strings; one unescaped quote
    would make Prometheus reject the entire scrape."""
    r = obs.MetricsRegistry()
    r.counter("retpu_x_total").labels('a"b\\c\nd').inc()
    txt = r.render_prometheus()
    assert 'tenant="a\\"b\\\\c\\nd"' in txt
    assert '\n' not in txt.split("retpu_x_total{")[1].split("}")[0]


# -- flight recorder --------------------------------------------------------

def _feed(fr, n, total=0.01, start=0):
    for i in range(n):
        out = fr.record({"flush_id": start + i, "total": total,
                         "unpack": total / 2})
        assert out is None, "healthy flush must not trigger"


def test_flight_trigger_ring_bound_and_dump_roundtrip(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("RETPU_OBS_DUMP_DIR", str(tmp_path))
    fr = obs.FlightRecorder(capacity=64, min_samples=16,
                            refresh_every=4, min_dump_interval_s=0.0,
                            name="t")
    _feed(fr, 32)
    snap = fr.record({"flush_id": 999, "total": 0.2,
                      "device_d2h": 0.19, "unpack": 0.01})
    assert snap is not None and fr.anomalies == 1
    trig = snap["trigger"]
    assert trig["flush_id"] == 999
    assert trig["ratio"] >= trig["threshold"] == 5.0
    assert trig["dominant_mark"] == "device_d2h"
    # ring bound holds under sustained load
    _feed(fr, 300, start=1000)
    assert len(fr.records) == 64
    # the dump file round-trips: schema, the ring (trigger flush
    # included), and the box fingerprint
    with open(snap["path"]) as f:
        data = json.load(f)
    assert data["schema"] == DUMP_SCHEMA
    assert data["trigger"]["flush_id"] == 999
    assert any(r.get("flush_id") == 999 for r in data["ring"])
    # schema v2 sections present even without an extras provider
    assert data["slow_ops"] == [] and data["compile_events"] == []
    box = data["box"]
    assert box["schema"] == "retpu-box-fingerprint-v1"
    assert box["cpu_count"] == os.cpu_count()
    assert "jax" in box and "retpu_knobs" in box
    assert "loadavg" in box


def test_flight_trigger_unarmed_before_min_samples():
    fr = obs.FlightRecorder(min_samples=32, refresh_every=4,
                            min_dump_interval_s=0.0)
    _feed(fr, 8)
    assert fr.record({"flush_id": 9, "total": 5.0}) is None
    assert fr.anomalies == 0


def test_flight_trigger_rate_limited():
    """The rate limit bounds DUMPS, not the anomaly counter: during
    a sustained incident every trigger firing still counts."""
    fr = obs.FlightRecorder(min_samples=8, refresh_every=2,
                            min_dump_interval_s=3600.0)
    _feed(fr, 16)
    assert fr.record({"flush_id": 1, "total": 1.0}) is not None
    assert fr.record({"flush_id": 2, "total": 1.0}) is None
    assert fr.anomalies == 2
    assert len(fr.dumps) == 1


def test_injected_slow_flush_dumps_on_live_service(tmp_path,
                                                   monkeypatch):
    """Acceptance: an injected >5x-p50 flush on a REAL service
    produces a flight dump with the per-flush ring and the box
    fingerprint."""
    monkeypatch.setenv("RETPU_OBS_DUMP_DIR", str(tmp_path))
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 8, tick=None,
                                 max_ops_per_tick=2)
    svc.flight = obs.FlightRecorder(min_samples=8, refresh_every=2,
                                    min_dump_interval_s=0.0,
                                    name="svc")
    for i in range(12):
        fut = svc.kput(i % 4, "k", b"v%d" % i)
        while not fut.done:
            svc.flush()
    assert svc.flight.anomalies == 0, \
        "healthy flushes must not trigger"
    # inject the stall at the d2h seam (the deterministic injection
    # point the pipeline tests use) — 6x the recorder's own rolling
    # p50 guarantees the trigger fires regardless of box speed
    stall = max(6.0 * svc.flight._p50, 0.05)
    orig = svc._fetch_packed

    def slow_fetch(fl):
        time.sleep(stall)
        return orig(fl)

    monkeypatch.setattr(svc, "_fetch_packed", slow_fetch)
    fut = svc.kput(0, "k", b"slow")
    while not fut.done:
        svc.flush()
    assert svc.flight.anomalies >= 1
    snap = svc.flight.dumps[-1]
    assert snap["schema"] == DUMP_SCHEMA
    assert snap["box"]["cpu_count"] == os.cpu_count()
    assert len(snap["ring"]) >= 8
    assert os.path.exists(snap["path"])
    # schema v2: the live service's dump carries the per-op ring
    # tail (slowest acked ops, stage splits, flush-id joins).  The
    # very slowest row is the first-compile-era op (its queue wait
    # ate the XLA compile — itself a correct attribution); the
    # STALLED op appears in the tail with its flush stage dominating
    assert snap["slow_ops"], "per-op tail section missing"
    assert all(o["flush_id"] > 0 for o in snap["slow_ops"])
    stalled = [o for o in snap["slow_ops"]
               if o["ms"] >= stall * 1e3 * 0.9
               and o["stages_ms"]["flush"]
               >= o["stages_ms"]["queue_wait"]]
    assert stalled, snap["slow_ops"]
    # compile-event section present and well-formed (entries only
    # when THIS process's jit caches were cold for these shapes —
    # earlier tests may have warmed them; the deterministic
    # un-warmed-bucket catch lives in test_opslo with a unique E)
    assert isinstance(snap["compile_events"], list)
    for e in snap["compile_events"]:
        assert e["phase"] in ("serve", "warmup") and e["fn"], e
    # the anomalous flush is queryable through the obs span API too
    tl = obs.timeline(snap["trigger"]["flush_id"])
    assert tl is not None and "leader" in tl
    svc.stop()


# -- cross-process flush tracing (live repgroup) ----------------------------

def test_flush_id_correlation_on_live_repgroup(tmp_path):
    """Acceptance: given a flush_id, the obs API returns the JOINED
    leader + replica timeline — and every replica apply span recorded
    during the run names a leader flush span."""
    from riak_ensemble_tpu.parallel import repgroup

    before = set(obs.SPANS.flush_ids())
    servers = [repgroup.ReplicaServer(4, 3, 8,
                                      data_dir=str(tmp_path / f"r{i}"),
                                      config=fast_test_config())
               for i in (1, 2)]
    svc = repgroup.ReplicatedService(
        WallRuntime(), 4, 1, 8, group_size=3,
        peers=[("127.0.0.1", s.repl_port) for s in servers],
        ack_timeout=30.0, max_ops_per_tick=4,
        config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover()
    futs = [svc.kput_many(e, ["a", "b"], [b"1", b"2"])
            for e in range(4)]
    while any(svc.queues):
        svc.flush()
    assert svc.heartbeat()
    assert all(f.done for f in futs)
    # acks settle at MAJORITY time — wait until BOTH lanes actually
    # reached the leader's applied position before reading their
    # span records (the straggler lane records when it lands)
    svc._drain_pending(block_all=True)
    want = (svc.core.applied_ge, svc.core.applied_seq)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        with servers[0]._lock, servers[1]._lock:
            if all((s.core.applied_ge, s.core.applied_seq) >= want
                   for s in servers):
                break
        time.sleep(0.02)

    def replica_sides(tl):
        # replica roles carry the lane tag ("replica@host:port") so
        # in-process lanes don't merge; match by prefix
        return {k: v for k, v in tl.items()
                if isinstance(k, str) and k.startswith("replica")}

    new = [fid for fid in obs.SPANS.flush_ids() if fid not in before]
    assert new, "the run recorded no flush timelines"
    joined = 0
    for fid in new:
        tl = obs.timeline(fid)
        reps = replica_sides(tl) if tl else {}
        if not reps:
            continue
        # every replica apply span names a leader flush span: the
        # SAME id carries both halves of the timeline
        assert "leader" in tl, f"replica-only timeline for {fid}"
        joined += 1
        for side in reps.values():
            r_spans = dict(side["spans"])
            assert "apply" in r_spans, tl
            if side.get("kind") == "delta":
                assert "validate" in r_spans, tl
        assert dict(tl["leader"]["spans"]), tl
    assert joined >= 1, "no flush joined leader and replica spans"
    # at least one data-bearing delta flush shows the full causal
    # chain on BOTH lanes: leader enqueue/build/ack + per-lane
    # replica scatter/rebuild/WAL (the lane tags keep the 2
    # in-process replicas' spans separate)
    full = []
    for fid in new:
        t = obs.timeline(fid)
        if not t:
            continue
        reps = {k: v for k, v in replica_sides(t).items()
                if v.get("kind") == "delta"}
        if reps and "repl_ack" in dict(t["leader"]["spans"]):
            full.append((t, reps))
    assert full, "no delta flush carries the end-to-end timeline"
    # both lanes drained above, so some delta flush must carry BOTH
    # lane-tagged replica records (distinct roles, not merged)
    both = [(t, r) for t, r in full if len(r) == 2]
    assert both, f"no flush tagged both lanes: {[list(r) for _, r in full]}"
    _some, reps = both[-1]
    for side in reps.values():
        for name in ("validate", "scatter", "rebuild", "wal_sync"):
            assert name in dict(side["spans"])
    svc.stop()
    for s in servers:
        s.stop()


# -- per-tenant attribution -------------------------------------------------

def test_two_tenant_attribution():
    """Acceptance: a hot and a quiet tenant are separable in the
    per-tenant ledger — ops, bytes, device-round share, p50/p99."""
    svc = BatchedEnsembleService(WallRuntime(), 8, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    svc.set_tenant_label(0, "hot")
    svc.set_tenant_label(1, "quiet")
    futs = []
    for i in range(40):
        futs.append(svc.kput(0, f"k{i % 4}", b"x" * 32))
    for i in range(4):
        futs.append(svc.kput(1, "q", b"y"))
    while any(svc.queues):
        svc.flush()
    assert all(f.done and f.value[0] == "ok" for f in futs)
    ts = svc.tenant_stats()
    hot, quiet = ts["hot"], ts["quiet"]
    assert hot["ops"] == 40 and quiet["ops"] == 4
    assert hot["commits"] == 40 and quiet["commits"] == 4
    assert hot["put_bytes"] == 40 * 32 and quiet["put_bytes"] == 4
    assert hot["device_rounds"] >= quiet["device_rounds"] > 0
    assert 0 < hot["device_round_share"] <= 1.0
    assert hot["p99_ms"] >= hot["p50_ms"] >= 0
    # leased fast reads count into the tenant ledger without a flush
    f = svc.kget(0, "k0")
    assert f.done and f.value[0] == "ok"
    assert svc.read_fastpath_hits >= 1
    assert svc.tenant_stats()["hot"]["ops"] == 41
    # the labels surface in every export: stats(), the registry
    # snapshot, and the Prometheus text
    assert "hot" in svc.stats()["tenants"]
    snap = svc.obs_registry.snapshot()
    assert snap["retpu_tenant_ops_total"]["hot"] == 41
    assert 'retpu_tenant_ops_total{tenant="hot"} 41' in \
        svc.obs_registry.render_prometheus()
    # a tenant spanning several rows is ONE tenant: rows sharing a
    # label aggregate instead of overwriting each other
    svc.set_tenant_label(2, "hot")
    f = svc.kput(2, "x", b"zz")
    while not f.done:
        svc.flush()
    agg = svc.tenant_stats()["hot"]
    assert agg["rows"] == [0, 2]
    assert agg["ops"] == 42 and agg["put_bytes"] == 40 * 32 + 2
    svc.stop()


def test_tenant_ledger_resets_on_row_recycle():
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 8, tick=None,
                                 max_ops_per_tick=2, dynamic=True)
    row = svc.create_ensemble("t1")
    fut = svc.kput(row, "k", b"v")
    while not fut.done:
        svc.flush()
    assert svc.tenant_stats()["t1"]["ops"] == 1
    assert svc.destroy_ensemble("t1")
    row2 = svc.create_ensemble("t2")
    assert row2 == row  # recycled
    assert svc.tenant_ops[row] == 0, \
        "a recycled row must start with a clean tenant ledger"
    assert "t1" not in svc.tenant_stats()
    svc.stop()


# -- RETPU_OBS=0 short-circuit ---------------------------------------------

def test_obs_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("RETPU_OBS", "0")
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 8, tick=None,
                                 max_ops_per_tick=2)
    fut = svc.kput(0, "k", b"v")
    while not fut.done:
        svc.flush()
    assert fut.value[0] == "ok"
    assert svc.stats()["obs_enabled"] is False
    assert not svc.flight.records
    assert int(svc.tenant_ops.sum()) == 0
    assert int(svc._tenant_lat.sum()) == 0
    svc.stop()


# -- Tracer: bounded finished ring + registry fold --------------------------

def test_tracer_finished_ring_bounded_and_registry_fold():
    from riak_ensemble_tpu.utils.trace import Tracer

    class _RT:
        now = 0.0
        trace = None

    rt = _RT()
    reg = obs.MetricsRegistry()
    tr = Tracer(rt, max_finished=16, registry=reg).install()
    for i in range(100):
        rt.now = float(i)
        sid = tr.begin("op", 0)
        rt.now = float(i) + 0.5
        tr.finish(sid, "ok")
        tr._on_event("tick", {})
    # the finished ring is bounded; the counters stay exact
    assert len(tr.finished) == 16
    assert tr.counters["span:op"] == 100
    assert tr.counters["tick"] == 100
    # the registry mirror: event counts + span duration histogram
    snap = reg.snapshot()
    assert snap["retpu_trace_events_total"]["tick"] == 100
    h = reg.histogram("retpu_trace_span_ms").labels("op")
    assert h.count == 100
    assert tr.percentiles("op")[0.5] == 0.5
    tr.uninstall()


# -- svcnode health verb ----------------------------------------------------

def test_svcnode_health_verb():
    """The ensemble-health verb over the wire: service summary and
    per-row detail, host-mirror-sourced (no flush needed to answer),
    with hostile ensemble indices rejected."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    async def run():
        server = await svcnode.serve(4, 3, 8, port=0, tick=0.002,
                                     config=fast_test_config())
        client = svcnode.ServiceClient(server.host, server.port)
        await client.connect()
        try:
            r = await client.kput(1, "k", b"v")
            assert r[0] == "ok"
            h = await client.health()
            assert h["schema"] == "retpu-health-v1"
            assert h["n_ens"] == 4
            assert h["ensembles_with_leader"] >= 1
            assert h["queued_ops"] == 0
            assert isinstance(h["pending_writes"], int)
            row = await client.health(1)
            assert row["ens"] == 1 and row["leader"] >= 0
            assert row["committed_epoch"] >= 1
            assert row["elections"] >= 1
            assert row["corrupt"] is False
            assert row["lease_valid"] in (True, False)
            # flushes advance the flush counter, not the verb: a
            # health read is zero-device-round (flushes unchanged
            # modulo the server's own tick loop serving real ops)
            bad = await client.call("health", 99)
            assert bad == ("error", "bad-request")
            bad2 = await client.call("health", -1)
            assert bad2 == ("error", "bad-request")
        finally:
            await client.close()
            await server.stop()

    asyncio.run(run())


# -- svcnode metrics verb ---------------------------------------------------

def test_svcnode_metrics_verb():
    import asyncio

    from riak_ensemble_tpu import svcnode

    async def run():
        server = await svcnode.serve(4, 3, 8, port=0, tick=0.002,
                                     config=fast_test_config())
        client = svcnode.ServiceClient(server.host, server.port)
        await client.connect()
        try:
            r = await client.kput(0, "k", b"v")
            assert r[0] == "ok"
            snap = await client.metrics()
            assert isinstance(snap, dict)
            assert snap["retpu_flushes_total"] >= 1
            assert snap["retpu_ops_served_total"] >= 1
            assert "retpu_flush_total_ms" in snap
            txt = await client.metrics("prometheus")
            assert isinstance(txt, str)
            assert "# TYPE retpu_flushes_total counter" in txt
        finally:
            await client.close()
            await server.stop()

    asyncio.run(run())
