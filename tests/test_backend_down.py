"""Backend failure handling: the handle_down / reset path.

The reference reacts to a crashed backend helper process with
``module_handle_down`` → ``{reset, ...}`` → ``step_down``
(src/riak_ensemble_peer.erl:1919-1948; behaviour contract
src/riak_ensemble_backend.erl:84-93): the peer abandons leadership and
re-probes, re-establishing state from the quorum.
"""

import pytest

from riak_ensemble_tpu.backend import BasicBackend, register_backend
from riak_ensemble_tpu.runtime import Actor
from riak_ensemble_tpu.testing import Cluster, make_peers


class _StoreActor(Actor):
    """Stand-in for an external storage process a backend leans on."""

    def handle(self, msg):
        pass


class HelperBackend(BasicBackend):
    """BasicBackend that declares a helper actor; its death resets the
    peer (the eleveldb-crashed analog)."""

    down_events = []

    def __init__(self, ensemble, peer_id, args=()):
        super().__init__(ensemble, peer_id, ())
        runtime, node = args
        self.helper_name = ("store", ensemble, repr(peer_id))
        if runtime.whereis(self.helper_name) is None:
            _StoreActor(runtime, self.helper_name, node)

    def monitored(self):
        return (self.helper_name,)

    def handle_down(self, ref, pid, reason):
        type(self).down_events.append((self.peer_id, ref))
        if ref == self.helper_name:
            self.data = {}          # storage gone with the process
            return ("reset",)
        return False


@pytest.fixture(autouse=True)
def _fresh_events():
    HelperBackend.down_events = []
    register_backend("helper", HelperBackend)


def _cluster_with_helpers():
    c = Cluster(seed=11)
    peers = make_peers(3)
    c.create_ensemble(
        "demo", peers, backend="helper",
        backend_args=(c.runtime, peers[0].node))
    # give each peer its own helper on its own node
    return c, peers


def test_helper_death_resets_leader_and_reelects():
    c, peers = _cluster_with_helpers()
    leader = c.wait_stable("demo")
    c.kput_ok("demo", "k", b"v")

    # Kill the LEADER's helper process mid-load.
    lp = c.peer("demo", leader)
    c.runtime.stop_actor(lp.mod.helper_name)
    c.runtime.run_for(0.1)

    # handle_down fired on the leader and it stepped down (reset).
    assert any(pid == leader for pid, _ in HelperBackend.down_events)
    assert lp.fsm_state != "leading"

    # The ensemble re-elects (possibly the same peer after re-probe)
    # and serves the committed value from the quorum.
    new = c.wait_stable("demo")
    assert c.kget_value("demo", "k") == b"v"
    # The reset peer's local store was wiped; a fresh read repairs it
    # through the quorum read path, so writes continue to commit.
    c.kput_ok("demo", "k", b"v2")
    assert c.kget_value("demo", "k") == b"v2"


def test_follower_helper_death_does_not_depose_leader():
    c, peers = _cluster_with_helpers()
    leader = c.wait_stable("demo")
    c.kput_ok("demo", "k", b"v")
    follower = next(p for p in peers if p != leader)
    fp = c.peer("demo", follower)
    c.runtime.stop_actor(fp.mod.helper_name)
    c.runtime.run_for(0.1)
    assert any(pid == follower for pid, _ in HelperBackend.down_events)
    # Leader unaffected; service continues.
    assert c.leader_id("demo") == leader
    assert c.kget_value("demo", "k") == b"v"


def test_unrelated_down_is_ignored():
    """handle_down returning False must leave the peer alone
    (the not-mine branch, peer.erl:1940-1942)."""
    c, peers = _cluster_with_helpers()
    leader = c.wait_stable("demo")
    lp = c.peer("demo", leader)
    other = ("store", "demo", "unrelated")
    _StoreActor(c.runtime, other, peers[0].node)
    lp.monitor_backend(other)
    c.runtime.stop_actor(other)
    c.runtime.run_for(0.1)
    assert any(ref == other for _, ref in HelperBackend.down_events)
    assert lp.fsm_state == "leading"
    assert c.leader_id("demo") == leader


def test_peer_stop_releases_backend_monitors():
    """A backend helper can outlive its peers; stopping a peer must
    demonitor the helper or every peer restart leaks a closure pinning
    the dead Peer (mirror of the msg.py lazy-collector fix)."""
    from riak_ensemble_tpu.peer import peer_name

    c, peers = _cluster_with_helpers()
    c.wait_stable("demo")

    victim = peers[0]
    helper = c.peer("demo", victim).mod.helper_name
    assert len(c.runtime._monitors.get(helper, [])) == 1

    c.runtime.stop_actor(peer_name("demo", victim))
    c.runtime.run_for(0.5)
    assert c.runtime.whereis(helper) is not None  # helper outlives peer
    assert len(c.runtime._monitors.get(helper, [])) == 0
