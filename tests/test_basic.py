"""basic_test.erl parity: 3-peer ensemble, put/get, leader suspension,
re-election, get again (test/basic_test.erl:5-24) — the minimum-slice
acceptance test — plus singleton-ensemble and kv-op coverage."""

import pytest

from riak_ensemble_tpu.testing import Cluster, make_peers
from riak_ensemble_tpu.types import NOTFOUND


def test_singleton_ensemble():
    c = Cluster(seed=1)
    (pid,) = make_peers(1)
    c.create_ensemble("ens", [pid])
    leader = c.wait_stable("ens")
    assert leader == pid
    c.kput_ok("ens", "k", b"v1")
    assert c.kget_value("ens", "k") == b"v1"


def test_basic_three_peers():
    c = Cluster(seed=2)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")

    c.kput_ok("ens", "test", b"current")
    assert c.kget_value("ens", "test") == b"current"

    # Suspend the leader; a new one must take over.
    c.suspend_peer("ens", leader)
    c.runtime.run_for(0.1)

    def new_leader():
        lid = c.leader_id("ens")
        return lid is not None and lid != leader
    assert c.runtime.run_until(new_leader, 60.0), "no re-election"
    c.wait_stable("ens")
    assert c.leader_id("ens") != leader

    # Value survives the failover.
    assert c.kget_value("ens", "test") == b"current"

    # Resume the old leader; it must rejoin as follower/catch up, and
    # the ensemble keeps serving.
    c.resume_peer("ens", leader)
    c.runtime.run_for(2.0)
    c.kput_ok("ens", "test", b"updated")
    assert c.kget_value("ens", "test") == b"updated"


def test_kget_notfound_skips_tombstone():
    c = Cluster(seed=3)
    c.create_ensemble("ens", make_peers(3))
    c.wait_stable("ens")
    r = c.kget("ens", "missing")
    assert r[0] == "ok" and r[1].value is NOTFOUND


def test_kput_once_and_update():
    c = Cluster(seed=4)
    c.create_ensemble("ens", make_peers(3))
    c.wait_stable("ens")

    r = c.kput_once("ens", "k", b"a")
    assert r[0] == "ok"
    # Second put_once fails the precondition.
    assert c.kput_once("ens", "k", b"b") == "failed"

    cur = c.kget("ens", "k")[1]
    r = c.kupdate("ens", "k", cur, b"c")
    assert r[0] == "ok"
    assert c.kget_value("ens", "k") == b"c"

    # Stale CAS (old version) fails.
    assert c.kupdate("ens", "k", cur, b"d") == "failed"


def test_kmodify_and_delete():
    c = Cluster(seed=5)
    c.create_ensemble("ens", make_peers(3))
    c.wait_stable("ens")

    r = c.kmodify("ens", "ctr", lambda vsn, v: v + 1, 0)
    assert r[0] == "ok" and r[1].value == 1
    r = c.kmodify("ens", "ctr", lambda vsn, v: v + 1, 0)
    assert r[0] == "ok" and r[1].value == 2

    c.kdelete("ens", "ctr")
    got = c.kget("ens", "ctr")
    assert got[0] == "ok" and got[1].value is NOTFOUND

    # safe delete: CAS on current version
    c.kput_ok("ens", "d", b"x")
    cur = c.kget("ens", "d")[1]
    r = c.ksafe_delete("ens", "d", cur)
    assert r[0] == "ok"
    assert c.kget("ens", "d")[1].value is NOTFOUND


def test_multi_worker_pool():
    """peer_workers > 1: distinct keys proceed via hash-partitioned
    workers; same-key ops stay serialized (async/3 routing,
    peer.erl:1220-1225)."""
    from riak_ensemble_tpu.config import fast_test_config

    cfg = fast_test_config()
    cfg.peer_workers = 4
    c = Cluster(seed=8, config=cfg)
    c.create_ensemble("ens", make_peers(3))
    c.wait_stable("ens")
    for i in range(12):
        c.kput_ok("ens", f"k{i}", f"v{i}".encode())
    for i in range(12):
        assert c.kget_value("ens", f"k{i}") == f"v{i}".encode()
    # same-key CAS sequence stays correct
    r = c.kput_once("ens", "cas", b"a")
    assert r[0] == "ok"
    cur = c.kget("ens", "cas")[1]
    assert c.kupdate("ens", "cas", cur, b"b")[0] == "ok"
    assert c.kupdate("ens", "cas", cur, b"c") == "failed"


def test_forwarded_request_never_bounces():
    """A "fwd"-wrapped request (a follower already forwarded it once)
    is handled by a leader and nacked by anyone else — never forwarded
    a second hop, so two followers with mutually stale fact.leader
    can't ping-pong one request (peer.erl:864-867 is one hop too)."""
    from riak_ensemble_tpu.peer import peer_name, sync_send_event

    c = Cluster(seed=11)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")
    follower = next(p for p in peers if p != leader)

    r = sync_send_event(c.runtime, peer_name("ens", leader),
                        ("fwd", ("overwrite", "fk", b"fv")), timeout=10.0)
    assert r[0] == "ok", r
    assert c.kget_value("ens", "fk") == b"fv"

    r = sync_send_event(c.runtime, peer_name("ens", follower),
                        ("fwd", ("overwrite", "fk", b"xx")), timeout=10.0)
    assert r == "nack", r
    assert c.kget_value("ens", "fk") == b"fv"
