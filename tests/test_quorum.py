"""Quorum predicate: hand cases + scalar-vs-batched differential test.

The scalar version encodes riak_ensemble_msg:quorum_met/5 semantics
(msg.erl:377-418); the batched kernel must agree on every input.
"""

import random

import numpy as np
import pytest

from riak_ensemble_tpu.ops.quorum import (
    MET, UNDECIDED, NACK, quorum_met, quorum_met_batch, views_to_mask,
)


def P(i):
    return ("p%d" % i, "node%d" % i)


class TestScalar:
    def test_empty_views_met(self):
        assert quorum_met([], P(0), []) == MET

    def test_self_counts(self):
        # 3 members, self is one: one more valid reply reaches 2/3 quorum.
        views = [[P(0), P(1), P(2)]]
        assert quorum_met([], P(0), views) == UNDECIDED
        assert quorum_met([(P(1), "ok")], P(0), views) == MET

    def test_self_not_member(self):
        views = [[P(1), P(2), P(3)]]
        assert quorum_met([(P(1), "ok")], P(0), views) == UNDECIDED
        assert quorum_met([(P(1), "ok"), (P(2), "ok")], P(0), views) == MET

    def test_other_mode_excludes_self(self):
        # 'other': majority excluding self (exchange uses this when its
        # own tree is untrusted).
        views = [[P(0), P(1), P(2)]]
        assert quorum_met([(P(1), "ok")], P(0), views, "other") == UNDECIDED
        assert quorum_met([(P(1), "ok"), (P(2), "ok")], P(0), views,
                          "other") == MET

    def test_all_mode(self):
        views = [[P(0), P(1), P(2)]]
        r = [(P(1), "ok")]
        assert quorum_met(r, P(0), views, "all") == UNDECIDED
        r = [(P(1), "ok"), (P(2), "ok")]
        assert quorum_met(r, P(0), views, "all") == MET

    def test_nack_majority(self):
        views = [[P(0), P(1), P(2)]]
        r = [(P(1), "nack"), (P(2), "nack")]
        assert quorum_met(r, P(0), views) == NACK

    def test_all_heard_no_quorum_nacks(self):
        # 5 members, self + 1 valid + 3 nacks = everyone heard, quorum
        # (3) not met -> NACK via the heard+nacks==members branch.
        views = [[P(0), P(1), P(2), P(3), P(4)]]
        r = [(P(1), "ok"), (P(2), "nack"), (P(3), "nack"), (P(4), "nack")]
        assert quorum_met(r, P(0), views) == NACK

    def test_joint_views_all_must_meet(self):
        v1 = [P(0), P(1), P(2)]
        v2 = [P(3), P(4), P(5)]
        r = [(P(1), "ok")]
        assert quorum_met(r, P(0), [v1, v2]) == UNDECIDED
        r = [(P(1), "ok"), (P(3), "ok"), (P(4), "ok")]
        assert quorum_met(r, P(0), [v1, v2]) == MET

    def test_joint_later_view_nack_hidden_by_earlier_undecided(self):
        # Reference recursion: if view 1 is undecided it never looks at
        # view 2, so a nack-failing later view still reports UNDECIDED.
        v1 = [P(0), P(1), P(2)]
        v2 = [P(3), P(4), P(5)]
        r = [(P(3), "nack"), (P(4), "nack")]
        assert quorum_met(r, P(0), [v1, v2]) == UNDECIDED
        # Once view 1 met, view 2's nacks surface.
        r += [(P(1), "ok")]
        assert quorum_met(r, P(0), [v1, v2]) == NACK


class TestBatchedDifferential:
    @pytest.mark.parametrize("required", ["quorum", "all", "all_or_quorum",
                                          "other"])
    def test_random_agreement(self, required):
        from riak_ensemble_tpu.ops.quorum import REQUIRED_MODES
        rng = random.Random(1000 + REQUIRED_MODES.index(required))
        M, V = 7, 3
        peers = [P(i) for i in range(M)]
        for trial in range(200):
            n_views = rng.randint(1, V)
            views_idx = []
            for _ in range(n_views):
                size = rng.randint(1, M)
                views_idx.append(sorted(rng.sample(range(M), size)))
            self_i = rng.randrange(-1, M)
            self_id = peers[self_i] if self_i >= 0 else ("nobody", "x")
            # Random reply pattern: each peer unheard / valid / nack.
            valid = np.zeros(M, bool)
            nack = np.zeros(M, bool)
            replies = []
            for i in range(M):
                roll = rng.random()
                if peers[i] == self_id:
                    continue  # self never replies to itself via transport
                if roll < 0.4:
                    valid[i] = True
                    replies.append((peers[i], "ok"))
                elif roll < 0.6:
                    nack[i] = True
                    replies.append((peers[i], "nack"))
            views = [[peers[i] for i in vi] for vi in views_idx]
            expect = quorum_met(replies, self_id, views, required)
            mask = views_to_mask(views_idx, V, M)
            got = int(quorum_met_batch(valid, nack, mask,
                                       np.int32(self_i), required))
            assert got == expect, (
                f"trial={trial} views={views_idx} self={self_i} "
                f"valid={valid} nack={nack} expect={expect} got={got}")

    def test_vmapped_batch_shape(self):
        E, V, M = 32, 2, 5
        rng = np.random.RandomState(0)
        valid = rng.rand(E, M) < 0.5
        nack = (~valid) & (rng.rand(E, M) < 0.3)
        mask = np.zeros((E, V, M), bool)
        mask[:, 0, :] = True
        self_idx = np.zeros(E, np.int32)
        out = quorum_met_batch(valid, nack, mask, self_idx)
        assert out.shape == (E,)
        for e in range(E):
            peers = [P(i) for i in range(M)]
            replies = [(peers[i], "ok") for i in range(M) if valid[e, i]]
            replies += [(peers[i], "nack") for i in range(M) if nack[e, i]]
            assert int(out[e]) == quorum_met(replies, peers[0],
                                             [peers], "quorum")


class TestExtraCheck:
    def test_extra_gates_met(self):
        # extra evaluated only once all views met (msg.erl:382-388);
        # False maps to UNDECIDED (keep collecting), never NACK.
        views = [[P(0), P(1), P(2)]]
        replies = [(P(1), "obj")]
        assert quorum_met(replies, P(0), views, "quorum",
                          extra=lambda rs: False) == UNDECIDED
        assert quorum_met(replies, P(0), views, "quorum",
                          extra=lambda rs: True) == MET

    def test_extra_not_consulted_before_views_met(self):
        views = [[P(0), P(1), P(2)]]
        calls = []

        def extra(rs):
            calls.append(rs)
            return True

        assert quorum_met([], P(0), views, "quorum", extra=extra) == UNDECIDED
        assert calls == []

    def test_extra_receives_all_replies_unfiltered(self):
        # The reference passes the full reply list (incl. non-members
        # and nacks) to Extra (msg.erl:382-388).
        views = [[P(0), P(1)]]
        replies = [(P(1), "obj"), (P(9), "stranger"), (P(1), "nack")]
        seen = []
        quorum_met(replies, P(0), views, "quorum",
                   extra=lambda rs: seen.append(list(rs)) or True)
        assert seen and seen[0] == replies


def test_lazy_collector_releases_owner_monitor():
    """Every lazy_send_all (ping_quorum) must drop its owner-death
    monitor when the collector finishes, or a long-lived leader
    accumulates one dead closure per call forever."""
    from riak_ensemble_tpu.peer import peer_name
    from riak_ensemble_tpu.testing import Cluster, make_peers

    c = Cluster(seed=23)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")
    lname = peer_name("ens", leader)

    def n_monitors():
        return len(c.runtime._monitors.get(lname, []))

    from riak_ensemble_tpu.peer import sync_send_event

    base = n_monitors()
    for _ in range(10):
        r = sync_send_event(c.runtime, lname, ("ping_quorum",),
                            timeout=10.0)
        assert len(r) >= 2, r
    c.runtime.run_for(1.0)
    assert n_monitors() <= base + 1, (base, n_monitors())
