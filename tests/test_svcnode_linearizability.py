"""sc.erl over the wire: concurrent TCP clients vs a live svcnode.

The reference's real linearizability test (test/sc.erl) drives an
EXTERNAL riak cluster over protobuf clients with concurrent workers
and checks every acked write is observed (prop_sc:835-880).  The
in-process service sweeps cover the engine/service semantics; this
one covers the WIRE: N pipelined ServiceClients race puts/gets/
deletes over TCP against a live svcnode while a nemesis flaps peers
under the service, one client dies mid-stream (its in-flight ops
resolve DISCONNECTED — ambiguous, exactly like a timed-out protobuf
call), and the plausible-value model must accept the whole history
plus a quiesced read-back.
"""

import asyncio
import itertools

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import svcnode  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.linearizability import KeyModel  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

N_ENS, N_PEERS, N_KEYS, N_WORKERS, OPS = 4, 5, 3, 4, 30


async def _scenario(seed: int) -> None:
    server = await svcnode.serve(N_ENS, N_PEERS, 16, port=0,
                                 config=fast_test_config())
    svc = server.svc
    models = {(e, k): KeyModel(f"{e}/key{k}")
              for e in range(N_ENS) for k in range(N_KEYS)}
    vals = itertools.count(1)
    stopped = []

    async def nemesis():
        rng = np.random.default_rng(seed + 1)
        down = {}
        while not stopped:
            await asyncio.sleep(0.01)
            r = rng.random()
            if r < 0.35 and down:
                e = list(down)[int(rng.integers(len(down)))]
                svc.set_peer_up(e, down.pop(e), True)
            elif r < 0.7:
                e = int(rng.integers(N_ENS))
                if e not in down and svc.leader_np[e] >= 0:
                    p = int(svc.leader_np[e])
                    svc.set_peer_up(e, p, False)
                    down[e] = p
        for e, p in down.items():
            svc.set_peer_up(e, p, True)

    def settle_write(m, op_id, res):
        if isinstance(res, tuple) and res[0] == "ok":
            m.ack_write(op_id)
        elif res == svcnode.ServiceClient.DISCONNECTED:
            m.timeout_write(op_id)   # ambiguous: may have committed
        else:
            m.fail_write(op_id)      # definitive service rejection

    async def worker(wid: int, die_early: bool):
        rng = np.random.default_rng(seed * 100 + wid)
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        for i in range(OPS):
            if die_early and i == OPS // 2:
                # client dies mid-pipeline: pending ops must resolve
                # DISCONNECTED (ambiguous), never hang or mis-ack
                await c.close()
                return
            e = int(rng.integers(N_ENS))
            key = f"key{int(rng.integers(N_KEYS))}"
            m = models[(e, int(key[-1]))]
            r = rng.random()
            try:
                if r < 0.5:
                    v = b"v%d" % next(vals)
                    op = m.invoke_write(v)
                    settle_write(m, op, await c.kput(e, key, v,
                                                     timeout=15.0))
                elif r < 0.8:
                    res = await c.kget(e, key, timeout=15.0)
                    if isinstance(res, tuple) and res[0] == "ok":
                        m.ack_read(res[1])
                else:
                    op = m.invoke_write(NOTFOUND)
                    settle_write(m, op, await c.kdelete(e, key,
                                                        timeout=15.0))
            except asyncio.TimeoutError:
                if r < 0.5 or r >= 0.8:
                    m.timeout_write(op)
        await c.close()

    nem = asyncio.ensure_future(nemesis())
    await asyncio.gather(*[
        worker(w, die_early=(w == 0)) for w in range(N_WORKERS)])
    stopped.append(True)
    await nem

    # quiesce + read-back: every key must read a plausible value
    # (Violation otherwise — the "Data loss!" check)
    c = svcnode.ServiceClient(server.host, server.port)
    await c.connect()
    served = 0
    for (e, k), m in models.items():
        res = await c.kget(e, f"key{k}", timeout=20.0)
        if isinstance(res, tuple) and res[0] == "ok":
            m.ack_read(res[1])
            served += 1
    assert served == len(models), "quiesced read-back incomplete"
    await c.close()
    await server.stop()


@pytest.mark.parametrize("seed", conftest.soak_seeds([7101, 7102, 7103]))
def test_svcnode_concurrent_clients_linearizable(seed):
    asyncio.run(_scenario(seed))
