"""Native single-pass resolve kernel: fuzz equivalence vs the Python
oracle (docs/ARCHITECTURE.md §12).

The contract under test is BYTE-IDENTITY: with the kernel on
(``RETPU_NATIVE_RESOLVE=1``, the default) and off, the same op stream
must produce bit-identical unpacked result planes, mirror slabs
(``_slot_vsn``/``_inline_value``), WAL store bytes, and delta-frame
sections.  The Python implementations are the oracle; the kernel is
an optimization, never a semantic.
"""

import os
import pickle
import zlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")

from riak_ensemble_tpu import funref
from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.parallel import repgroup, resolve_native
from riak_ensemble_tpu.parallel.batched_host import (
    BatchedEnsembleService, WallRuntime, unpack_results,
)

needs_kernel = pytest.mark.skipif(
    resolve_native.get() is None,
    reason="native resolve kernel unavailable (no toolchain)")


def _pack_reference(won, quorum, corrupt, committed, get_ok, found,
                    value, vsn, want_vsn):
    """Host-side replica of _pack_results_body's layout (the d2h
    payload the kernel unpacks)."""
    flags = np.concatenate(
        [won.ravel(), quorum.ravel(), corrupt.ravel(),
         committed.ravel(), get_ok.ravel(),
         found.ravel()]).astype(bool)
    ints = [value.ravel().astype(np.int32)]
    if want_vsn:
        ints += [vsn[..., 0].ravel().astype(np.int32),
                 vsn[..., 1].ravel().astype(np.int32)]
    return np.concatenate([np.packbits(flags),
                           np.concatenate(ints).view(np.uint8)])


# -- 1) packed-result unpack -------------------------------------------------


@needs_kernel
@pytest.mark.parametrize("seed", range(3))
def test_unpack_fuzz_equivalence(seed):
    """Random packed planes through native vs Python unpack: every
    returned plane bit-identical across full-width, compacted
    (pack-gather) and sliced [K, A] layouts, want_vsn on and off."""
    nr = resolve_native.get()
    rng = np.random.default_rng(seed)
    for trial in range(60):
        e = int(rng.integers(4, 48))
        m = int(rng.integers(1, 6))
        k = int(rng.integers(0, 10))
        want_vsn = bool(rng.integers(0, 2))
        mode = int(rng.integers(0, 3))  # full / pack-gather / sliced
        if mode == 0:
            active, aw, sliced = None, e, False
        else:
            na = int(rng.integers(1, e))
            active = np.sort(
                rng.choice(e, na, replace=False)).astype(np.int32)
            aw = 8
            while aw < na:
                aw <<= 1
            aw = max(min(aw, e), na)
            sliced = mode == 2
        hw = aw if (sliced and active is not None) else e
        won = rng.integers(0, 2, hw).astype(bool)
        quorum = rng.integers(0, 2, hw).astype(bool)
        corrupt = rng.integers(0, 2, (hw, m)).astype(bool)
        committed = rng.integers(0, 2, (k, aw)).astype(bool)
        get_ok = rng.integers(0, 2, (k, aw)).astype(bool)
        found = rng.integers(0, 2, (k, aw)).astype(bool)
        value = rng.integers(-2**31, 2**31, (k, aw)).astype(np.int32)
        vsn = rng.integers(0, 2**31, (k, aw, 2)).astype(np.int32)
        flat = _pack_reference(won, quorum, corrupt, committed,
                               get_ok, found, value, vsn, want_vsn)
        a_width = 0 if active is None else aw
        ref = unpack_results(flat, e, m, k, want_vsn, active=active,
                             a_width=a_width, sliced=sliced)
        nat = nr.unpack(flat, e, m, k, want_vsn, active, a_width,
                        sliced)
        assert nat is not None
        for name, a, b in zip(
                ("won", "quorum", "corrupt", "committed", "get_ok",
                 "found", "value", "vsn"), ref, nat):
            if a is None:
                assert b is None, name
                continue
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                (seed, trial, name, mode)


@needs_kernel
def test_unpack_rejects_short_payload():
    """A truncated payload returns None (the caller falls back to the
    Python unpack, which raises the honest shape error)."""
    nr = resolve_native.get()
    assert nr.unpack(np.zeros((3,), np.uint8), 16, 3, 4, True, None,
                     0, False) is None


# -- 2/3) service-level equivalence (mirrors + WAL bytes) --------------------


def _workload(svc, rng, n_ens, k, rounds):
    """A mixed keyed op stream: batched puts/gets/CAS/deletes, scalar
    puts/gets (incl. want_vsn), device RMWs (inline slots) and
    RMW-to-zero tombstones.  Returns every future's resolved value in
    issue order (the client-visible half of the equivalence)."""
    out = []
    futs = []
    add1 = funref.ref("rmw:add", 1)
    set_zero = funref.ref("rmw:set", 0)
    for r in range(rounds):
        for e in range(n_ens):
            keys = [f"k{(r + i) % 11}" for i in range(k)]
            vals = [b"v%d.%d" % (r, i) for i in range(k)]
            if r == 2 and e == 0:
                # >= 64 KiB payload: CPython pickles it OUT of the
                # frame, so the native WAL arm must route this flush
                # to the Python encoder (byte-identity regression)
                vals[0] = b"P" * (1 << 16)
            pick = rng.integers(0, 7)
            if pick == 0:
                futs.append(svc.kput_many(e, keys, vals))
            elif pick == 1:
                futs.append(svc.kget_many(
                    e, keys, want_vsn=bool(rng.integers(0, 2))))
            elif pick == 2:
                futs.append(svc.kupdate_many(
                    e, keys[:2], [(0, 0), (0, 0)], vals[:2]))
            elif pick == 3:
                futs.append(svc.kdelete_many(e, keys[:3]))
            elif pick == 4:
                futs.append(svc.kmodify(e, f"ctr{r % 3}", add1, 0))
            elif pick == 5:
                # tombstone RMW: a computed 0 recycles the slot
                futs.append(svc.kmodify(e, f"ctr{r % 3}", set_zero, 0))
            else:
                futs.append(svc.kput(e, keys[0], vals[0]))
                futs.append(svc.kget(e, keys[1]))
        while any(svc.queues):
            svc.flush()
    svc.flush()
    for f in futs:
        assert f.done
        out.append(f.value)
    return out


def _run_arm(tmp_path, arm, seed, monkeypatch, wal=True):
    monkeypatch.setenv("RETPU_NATIVE_RESOLVE", arm)
    monkeypatch.setenv("RETPU_FAST_READS", "0")  # every read = round
    rng = np.random.default_rng(seed)
    kw = (dict(data_dir=str(tmp_path / f"arm{arm}"),
               wal_sync="buffer") if wal else {})
    svc = BatchedEnsembleService(WallRuntime(), 8, 3, 16, tick=None,
                                 max_ops_per_tick=8, **kw)
    if arm == "1" and resolve_native.get() is not None:
        assert svc._native_resolve is not None
    results = _workload(svc, rng, 8, 4, rounds=6)
    state = {
        "results": results,
        "vsn_ok": svc._slot_vsn_ok.copy(),
        "vsn_np": svc._slot_vsn_np.copy(),
        "inl_ok": svc._inline_value_ok.copy(),
        "inl_np": svc._inline_value_np.copy(),
        "inline_np": svc._inline_np.copy(),
        "inline_sets": [sorted(s) for s in svc._inline_slots],
        "native_flushes": svc.native_resolve_flushes,
        "fallback_flushes": svc.fallback_resolve_flushes,
    }
    if wal:
        state["wal_records"] = sorted(
            map(repr, svc._wal.records()))
        wal_dir = svc._wal.dir_path
        state["wal_files"] = {
            name: open(os.path.join(wal_dir, name), "rb").read()
            for name in sorted(os.listdir(wal_dir))}
    svc.stop()
    return state


@needs_kernel
@pytest.mark.parametrize("seed", range(2))
def test_service_equivalence_native_vs_fallback(tmp_path, seed,
                                                monkeypatch):
    """The whole resolve half, end to end: an identical mixed op
    stream through a native-arm and a fallback-arm service must yield
    identical client results, BIT-IDENTICAL mirror slabs, identical
    inline storage-class sets/slab, and byte-identical WAL files."""
    a = _run_arm(tmp_path, "1", seed, monkeypatch)
    b = _run_arm(tmp_path, "0", seed, monkeypatch)
    assert a["native_flushes"] > 0, "native arm never took the kernel"
    assert b["native_flushes"] == 0 and b["fallback_flushes"] > 0
    assert a["results"] == b["results"]
    assert np.array_equal(a["vsn_ok"], b["vsn_ok"])
    assert np.array_equal(a["vsn_np"][a["vsn_ok"]],
                          b["vsn_np"][b["vsn_ok"]])
    assert np.array_equal(a["inl_ok"], b["inl_ok"])
    assert np.array_equal(a["inl_np"][a["inl_ok"]],
                          b["inl_np"][b["inl_ok"]])
    assert np.array_equal(a["inline_np"], b["inline_np"])
    assert a["inline_sets"] == b["inline_sets"]
    assert a["wal_records"] == b["wal_records"]
    # byte-identity of the store files is the strongest form of the
    # WAL contract: the arena path appended the very same bytes
    assert a["wal_files"].keys() == b["wal_files"].keys()
    for name in a["wal_files"]:
        assert a["wal_files"][name] == b["wal_files"][name], name


@needs_kernel
def test_inline_set_slab_coherence(tmp_path, monkeypatch):
    """The `_inline_np` storage-class slab must mirror the
    `_inline_slots` sets exactly after a mixed workload (the kernel
    routes leased-GET refreshes through the slab)."""
    monkeypatch.setenv("RETPU_NATIVE_RESOLVE", "1")
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 16, tick=None,
                                 max_ops_per_tick=8)
    _workload(svc, np.random.default_rng(7), 4, 4, rounds=4)
    for e in range(4):
        assert set(np.flatnonzero(svc._inline_np[e]).tolist()) == \
            svc._inline_slots[e], e
    svc.stop()


@needs_kernel
def test_large_payload_falls_back_byte_identical(tmp_path,
                                                 monkeypatch):
    """A >= 64 KiB payload pickles out-of-frame in CPython; the
    native WAL arm must fall back for that flush and the store bytes
    must still match the oracle arm exactly."""
    files = {}
    for arm in ("1", "0"):
        monkeypatch.setenv("RETPU_NATIVE_RESOLVE", arm)
        d = str(tmp_path / f"big{arm}")
        svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8,
                                     tick=None, max_ops_per_tick=4,
                                     data_dir=d, wal_sync="buffer")
        futs = [svc.kput_many(0, ["big", "small"],
                              [b"B" * 70000, b"s"]),
                svc.kput_many(1, ["x"], [b"y"])]
        while any(svc.queues):
            svc.flush()
        assert all(r[0] == "ok" for f in futs for r in f.value)
        wal_dir = svc._wal.dir_path
        files[arm] = {
            name: open(os.path.join(wal_dir, name), "rb").read()
            for name in sorted(os.listdir(wal_dir))}
        svc.stop()
    assert files["1"].keys() == files["0"].keys()
    for name in files["1"]:
        assert files["1"][name] == files["0"][name], name


def test_exotic_keys_take_python_wal_path(tmp_path, monkeypatch):
    """Keys outside the kernel's pickle subset (tuples, non-ascii
    strs, ints) must fall back to the Python WAL encode — and restore
    correctly either way."""
    monkeypatch.setenv("RETPU_NATIVE_RESOLVE", "1")
    d = str(tmp_path / "svc")
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4, data_dir=d,
                                 wal_sync="buffer")
    futs = [svc.kput_many(0, [("tup", 1), "κλειδί", 7],
                          [b"a", b"b", b"c"]),
            svc.kput_many(1, ["plain"], [b"d"])]
    while any(svc.queues):
        svc.flush()
    assert all(r[0] == "ok" for f in futs for r in f.value)
    svc.stop()
    svc2 = BatchedEnsembleService.restore(WallRuntime(), d, tick=None)
    for e, key, want in ((0, ("tup", 1), b"a"), (0, "κλειδί", b"b"),
                         (0, 7, b"c"), (1, "plain", b"d")):
        f = svc2.kget(e, key)
        while not f.done:
            svc2.flush()
        assert f.value == ("ok", want), (key, f.value)
    svc2.stop()


# -- 4) delta-frame sections -------------------------------------------------


@needs_kernel
@pytest.mark.parametrize("seed", range(3))
def test_delta_entry_fuzz_equivalence(seed):
    """build_delta_entry with the kernel vs the numpy pipeline:
    identical section bytes, dtypes, CRC and byte count over random
    committed/kind/slot/value planes (wide and narrow index dtypes,
    empty planes included)."""
    nr = resolve_native.get()
    rng = np.random.default_rng(seed)
    for trial in range(40):
        e = int(rng.integers(2, 300))
        k = int(rng.integers(1, 18))
        n_slots = int(rng.choice([16, 300]))
        committed = rng.integers(0, 2, (k, e)).astype(bool)
        if trial % 6 == 0:
            committed[:] = False
        value = rng.integers(-1000, 1000, (k, e)).astype(np.int32)
        kind = rng.choice(
            [eng.OP_NOOP, eng.OP_PUT, eng.OP_GET, eng.OP_CAS,
             eng.OP_RMW], (k, e)).astype(np.int32)
        slot = rng.integers(0, n_slots, (k, e)).astype(np.int32)
        val = rng.integers(0, 1 << 20, (k, e)).astype(np.int32)
        quorum = rng.integers(0, 2, e).astype(bool)
        ref_e, ref_crc, ref_n = repgroup.build_delta_entry(
            3, k, committed, value, kind, slot, val, quorum, [],
            n_slots=n_slots, fid=9, native=None)
        nat_e, nat_crc, nat_n = repgroup.build_delta_entry(
            3, k, committed, value, kind, slot, val, quorum, [],
            n_slots=n_slots, fid=9, native=nr)
        assert nat_crc == ref_crc and nat_n == ref_n, (seed, trial)
        assert len(nat_e) == len(ref_e)
        for i, (x, y) in enumerate(zip(ref_e, nat_e)):
            if hasattr(x, "buf"):  # wire.Raw
                xa = np.frombuffer(x.buf, np.uint8)
                ya = np.frombuffer(y.buf, np.uint8)
                assert np.array_equal(xa, ya), (seed, trial, i)
            else:
                assert x == y, (seed, trial, i)


# -- 5) WAL pickle subset ----------------------------------------------------


@needs_kernel
def test_wal_encode_pickle_byte_identity():
    """The kernel's protocol-4 pickle templates vs pickle.dumps for
    the routed subset: short/long str keys, bytes/None payloads, the
    K/M/J int ranges, inline True/False."""
    nr = resolve_native.get()
    rng = np.random.default_rng(11)
    e_total, k = 9, 5
    cases = [
        ("a", b""), ("key%d" % 7, b"x" * 3), ("L" * 300, b"y" * 400),
        ("k", None), ("m" * 255, b"z"),
    ]
    n = len(cases)
    lane_j = rng.integers(0, k, n).astype(np.int32)
    lane_e = rng.integers(0, e_total, n).astype(np.int32)
    lane_slot = np.asarray([0, 255, 256, 65535, 65536], np.int32)
    lane_f2 = np.asarray([0, 1, 255, 65535, 2**31 - 1], np.int32)
    lane_inline = np.asarray([0, 1, 0, 1, 0], np.uint8)
    committed = np.ones((k, e_total), bool)
    value = rng.integers(-2**31, 2**31, (k, e_total)).astype(np.int32)
    vsn = rng.integers(0, 2**31, (k, e_total, 2)).astype(np.int32)
    keys = [c[0] for c in cases]
    pays = [c[1] for c in cases]
    key_len = np.asarray([len(s) for s in keys], np.int64)
    key_off = np.zeros((n,), np.int64)
    np.cumsum(key_len[:-1], out=key_off[1:])
    pay_len = np.asarray([-1 if p is None else len(p)
                          for p in pays], np.int64)
    pay_off = np.zeros((n,), np.int64)
    np.cumsum(np.maximum(pay_len, 0)[:-1], out=pay_off[1:])
    arena, idx = nr.wal_encode(
        e_total, lane_j, lane_e, lane_slot, lane_f2, lane_inline,
        np.zeros((n,), np.uint8), key_off, key_len,
        "".join(keys).encode(), pay_off, pay_len,
        b"".join(p for p in pays if p is not None),
        committed, value, vsn)
    raw = arena.tobytes()
    for i in range(n):
        j, e = int(lane_j[i]), int(lane_e[i])
        ko, kl, vo, vl = idx[i].tolist()
        kref = pickle.dumps(("kv", e, int(lane_slot[i])), protocol=4)
        f2 = int(value[j, e]) if lane_inline[i] else int(lane_f2[i])
        vref = pickle.dumps(
            (keys[i], f2, int(vsn[j, e, 0]), int(vsn[j, e, 1]),
             pays[i], bool(lane_inline[i])), protocol=4)
        assert raw[ko:ko + kl] == kref, i
        assert raw[vo:vo + vl] == vref, i
        assert pickle.loads(raw[vo:vo + vl]) == (
            keys[i], f2, int(vsn[j, e, 0]), int(vsn[j, e, 1]),
            pays[i], bool(lane_inline[i]))


# -- 6) degradation ----------------------------------------------------------


def test_knob_pins_fallback(monkeypatch):
    """RETPU_NATIVE_RESOLVE=0 pins the Python arm at construction."""
    monkeypatch.setenv("RETPU_NATIVE_RESOLVE", "0")
    assert resolve_native.get() is None
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    assert svc._native_resolve is None
    f = svc.kput(0, "k", b"v")
    while not f.done:
        svc.flush()
    assert f.value[0] == "ok"
    assert svc.fallback_resolve_flushes > 0
    assert svc.native_resolve_flushes == 0
    svc.stop()


def test_missing_so_degrades_to_python(monkeypatch):
    """A missing/unbuildable kernel .so must mean the Python fallback
    — never a crash, never a test failure (the satellite's graceful-
    degradation contract).  Simulated by pinning the loader's memo to
    'tried and failed'."""
    monkeypatch.setenv("RETPU_NATIVE_RESOLVE", "1")
    monkeypatch.setattr(resolve_native, "_instance", None)
    monkeypatch.setattr(resolve_native, "_instance_tried", True)
    assert resolve_native.get() is None
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    assert svc._native_resolve is None
    f = svc.kput(0, "k", b"v")
    g = svc.kget(0, "k")
    while not (f.done and g.done):
        svc.flush()
    assert f.value[0] == "ok" and g.value == ("ok", b"v")
    svc.stop()
