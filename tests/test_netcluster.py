"""Real multi-process cluster: three OS processes, one node each,
TCP transport (the DCN/host half of the distributed backend).

Brings up enable → join × 2 → cross-node ensemble → client K/V routed
across processes — the same sequence the simulator tests run, but over
real sockets with wall-clock timers (netruntime/netnode).
"""

import os
import socket
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NODE0_SCRIPT = """
import asyncio
from riak_ensemble_tpu.types import PeerId

async def main(node):
    r = await node.enable()
    assert r == "ok", r
    print("ENABLED", flush=True)
    for _ in range(600):
        if len(node.members()) >= 3:
            break
        await asyncio.sleep(0.1)
    assert len(node.members()) >= 3, node.members()
    print("MEMBERS_OK", flush=True)

    # Leader hint on node1: client ops from this process must route
    # cross-node.
    peers = [PeerId(1, "node1"), PeerId(0, "node0"), PeerId(2, "node2")]
    r = await node.create_ensemble("kv", peers)
    assert r == "ok", r

    r = ("error", "not_started")
    for _ in range(300):
        r = await node.kover("kv", "k", b"v1", timeout=3.0)
        if r[0] == "ok":
            break
        await asyncio.sleep(0.2)
    assert r[0] == "ok", r
    r = await node.kget("kv", "k", timeout=5.0)
    assert r[0] == "ok" and r[1].value == b"v1", r

    # CAS through the same path
    cur = r[1]
    r = await node.kupdate("kv", "k", cur, b"v2", timeout=5.0)
    assert r[0] == "ok", r
    r = await node.kget("kv", "k", timeout=5.0)
    assert r[0] == "ok" and r[1].value == b"v2", r

    print("RESULT_OK", flush=True)
    await asyncio.sleep(60)
"""

JOINER_SCRIPT = """
import asyncio

async def main(node):
    for _ in range(600):
        r = await node.join("node0", timeout=10.0)
        if r == "ok":
            break
        await asyncio.sleep(0.3)
    assert r == "ok", r
    print("JOINED", flush=True)
    await asyncio.sleep(120)
"""


NODE0_KILL_SCRIPT = """
import asyncio
from riak_ensemble_tpu.types import PeerId

async def main(node):
    assert (await node.enable()) == "ok"
    for _ in range(600):
        if len(node.members()) >= 3:
            break
        await asyncio.sleep(0.1)
    peers = [PeerId(1, "node1"), PeerId(0, "node0"), PeerId(2, "node2")]
    assert (await node.create_ensemble("kv", peers)) == "ok"
    r = ("error", "x")
    for _ in range(300):
        r = await node.kover("kv", "k", b"v1", timeout=3.0)
        if r[0] == "ok":
            break
        await asyncio.sleep(0.2)
    assert r[0] == "ok", r
    print("WROTE_V1", flush=True)

    # wait for the driver to kill node1 (leader hint), then keep
    # serving: a new leader must emerge from node0/node2
    await asyncio.sleep(3.0)
    r = ("error", "x")
    for _ in range(600):
        r = await node.kover("kv", "k", b"v2", timeout=2.0)
        if r[0] == "ok":
            break
        await asyncio.sleep(0.2)
    assert r[0] == "ok", r
    r = await node.kget("kv", "k", timeout=5.0)
    assert r[0] == "ok" and r[1].value == b"v2", r
    print("SURVIVED_KILL", flush=True)

    # node1 restarts from its data root; wait until the full ensemble
    # is healthy again (all three replicas answering = count 3)
    for _ in range(600):
        n = await node.runtime.await_future(
            node.manager.count_quorum("kv", timeout=2.0), 4.0)
        if n >= 3:
            break
        await asyncio.sleep(0.3)
    assert n >= 3, n
    print("RESULT_OK", flush=True)
    await asyncio.sleep(60)
"""

IDLE_SCRIPT = """
import asyncio

async def main(node):
    print("UP", flush=True)
    await asyncio.sleep(300)
"""


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def test_three_process_cluster(tmp_path):
    ports = _free_ports(3)
    peer_args = []
    for i, p in enumerate(ports):
        peer_args += ["--peer", f"node{i}=127.0.0.1:{p}"]

    scripts = {}
    for name, body in (("node0", NODE0_SCRIPT), ("node1", JOINER_SCRIPT),
                       ("node2", JOINER_SCRIPT)):
        path = tmp_path / f"{name}_script.py"
        path.write_text(body)
        scripts[name] = str(path)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The networked host is pure-Python; keep JAX out of these procs.
    procs = {}
    try:
        for i in range(3):
            name = f"node{i}"
            procs[name] = subprocess.Popen(
                [sys.executable, "-m", "riak_ensemble_tpu.netnode",
                 "--node", name, *peer_args, "--fast",
                 "--data-root", str(tmp_path / name),
                 "--script", scripts[name]],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=REPO)

        lines = []
        got_result = threading.Event()

        def reader():
            for line in procs["node0"].stdout:
                lines.append(line.strip())
                if "RESULT_OK" in line:
                    got_result.set()
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        ok = got_result.wait(timeout=150)
        assert ok, f"cluster never converged; node0 said: {lines!r}"
        assert "ENABLED" in lines and "MEMBERS_OK" in lines
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait(timeout=10)


def test_process_kill_and_restart(tmp_path):
    """Kill the leader's OS process mid-run: the survivors re-elect
    and keep serving; the restarted process reloads its persisted
    state (facts + cluster state) and rejoins the ensemble."""
    ports = _free_ports(3)
    peer_args = []
    for i, p in enumerate(ports):
        peer_args += ["--peer", f"node{i}=127.0.0.1:{p}"]

    scripts = {}
    for name, body in (("node0", NODE0_KILL_SCRIPT),
                       ("node1", JOINER_SCRIPT),
                       ("node2", JOINER_SCRIPT),
                       ("node1r", IDLE_SCRIPT)):
        path = tmp_path / f"{name}_script.py"
        path.write_text(body)
        scripts[name] = str(path)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(name, script):
        return subprocess.Popen(
            [sys.executable, "-m", "riak_ensemble_tpu.netnode",
             "--node", name, *peer_args, "--fast",
             "--data-root", str(tmp_path / name), "--script", script],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO)

    procs = {}
    try:
        procs["node0"] = spawn("node0", scripts["node0"])
        procs["node1"] = spawn("node1", scripts["node1"])
        procs["node2"] = spawn("node2", scripts["node2"])

        lines = []
        marks = {"WROTE_V1": threading.Event(),
                 "SURVIVED_KILL": threading.Event(),
                 "RESULT_OK": threading.Event()}

        def reader():
            for line in procs["node0"].stdout:
                lines.append(line.strip())
                for mark, ev in marks.items():
                    if mark in line:
                        ev.set()
                if "RESULT_OK" in line:
                    return

        threading.Thread(target=reader, daemon=True).start()

        # Deadlines are sized for a heavily loaded machine (the r2
        # full-suite run tripped a 90 s wait that passes in
        # isolation): the in-script retry loops dominate, and a
        # generous driver wait only costs time when the test is
        # genuinely broken.
        assert marks["WROTE_V1"].wait(240), f"no first write: {lines!r}"
        # kill the leader-hint node's process
        procs["node1"].kill()
        procs["node1"].wait(timeout=10)

        assert marks["SURVIVED_KILL"].wait(300), \
            f"no service after kill: {lines!r}"

        # restart node1 from its persisted data root
        procs["node1_restarted"] = spawn("node1", scripts["node1r"])
        assert marks["RESULT_OK"].wait(300), \
            f"restarted node never rejoined: {lines!r}"
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait(timeout=10)
