"""sc.erl-analog linearizability check for the BATCHED SERVICE path.

The scalar actor stack has its own workload checker
(test_linearizability.py); this one drives the same plausible-value
model (test/sc.erl get_post:112-148, prop_sc:835-880 postconditions)
against :class:`BatchedEnsembleService` — the engine-backed scale path
— under an up-mask nemesis: the leader is killed between enqueue and
flush (so the election folds into the same launch that carries the
ops), peers flap, and virtual time jumps past the lease so reads race
lease expiry.  Every seed is a reproducible schedule.
"""

import itertools

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.linearizability import KeyModel  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

N_ENS = 6
N_PEERS = 5
N_KEYS = 3
ROUNDS = 35


def _drain(svc, runtime, pending, max_flushes=10, tolerate=None,
           on_tolerated=None):
    """Flush until every submitted future resolves (queued ops past
    max_ops_per_tick ride later launches).  ``tolerate`` is a
    substring of flush errors to survive (the launch-failure nemesis);
    ``on_tolerated`` is called for each one."""
    for _ in range(max_flushes):
        if all(fut.done for _, _, _, fut, _ in pending):
            return
        try:
            svc.flush()
        except RuntimeError as exc:
            if tolerate is None or tolerate not in str(exc):
                raise
            if on_tolerated is not None:
                on_tolerated()
        runtime.run_for(0.001)
    raise AssertionError("ops never resolved")


def _apply_outcomes(pending):
    """Feed resolutions to the models in resolution (= device round)
    order.  Put/delete acks are linearization points; 'failed' is a
    DEFINITIVE no-op — the engine gates every replica write on the
    round's quorum commit (_kv_round put_commit), so a failed op can
    never partially land later.  fail_write keeps the checker strong:
    a timed-out value would stay plausible forever and mask exactly
    the stale-read/data-loss signals this sweep exists to catch."""
    for kind, model, op_id, fut, _payload in pending:
        r = fut.value
        if kind in ("put", "del"):
            if isinstance(r, tuple) and r[0] == "ok":
                model.ack_write(op_id)
            else:
                model.fail_write(op_id)
        else:  # get
            if isinstance(r, tuple) and r[0] == "ok":
                model.ack_read(r[1])
            # 'failed' read returned nothing: no model event


def _submit_batch(rng, svc, models, vals, vsns, seed):
    """One round of the concurrent workload, shared by every sweep:
    puts, CAS updates on the last acked vsn (sometimes stale — then
    they must fail cleanly), reads, and deletes."""
    pending = []
    for _ in range(int(rng.integers(2, 8))):
        e = int(rng.integers(N_ENS))
        k = int(rng.integers(N_KEYS))
        m = models[(e, k)]
        key = f"key{k}"
        op = rng.random()
        if op < 0.55:
            payload = f"{seed}-{next(vals)}".encode()
            op_id = m.invoke_write(payload)
            if op < 0.4:
                fut = svc.kput(e, key, payload)
            else:
                # all-or-nothing CAS against the engine's vsn check
                fut = svc.kupdate(e, key, vsns.get((e, k), (0, 0)),
                                  payload)
            if fut.done and fut.value == "failed":
                # pre-flush rejection (no slot): definitely a no-op
                m.fail_write(op_id)
            else:
                pending.append(("put", m, op_id, fut, payload))

            def _track(res, ek=(e, k)):
                if isinstance(res, tuple) and res[0] == "ok":
                    vsns[ek] = res[1]
            fut.add_waiter(_track)
        elif op < 0.85:
            pending.append(("get", m, None, svc.kget(e, key), None))
        else:
            op_id = m.invoke_write(NOTFOUND)
            fut = svc.kdelete(e, key)
            if fut.done:
                # no slot -> nothing to delete: an immediate ack of
                # the NOTFOUND state
                m.ack_write(op_id)
            else:
                pending.append(("del", m, op_id, fut, None))
    return pending


@pytest.mark.parametrize("seed", conftest.soak_seeds([701, 702, 703, 704, 705, 706]))
def test_service_linearizable_under_nemesis(seed):
    _nemesis_sweep(seed, pipeline_depth=1)


@pytest.mark.parametrize("seed", conftest.soak_seeds([711, 712, 713]))
def test_service_linearizable_under_nemesis_pipelined(seed):
    """The SAME nemesis sweep through the depth-2 launch pipeline
    (max_ops_per_tick=4 so rounds split across overlapped flushes):
    the async path must stay linearizable — results in submission
    order, WAL-free acks still quorum-gated, elections folded
    correctly after the pre-elect drain."""
    _nemesis_sweep(seed, pipeline_depth=2, max_k=4)


def _nemesis_sweep(seed, pipeline_depth, max_k=8):
    rng = np.random.default_rng(seed)
    runtime = Runtime(seed=seed)
    config = fast_test_config()
    svc = BatchedEnsembleService(runtime, N_ENS, N_PEERS, n_slots=8,
                                 tick=None, max_ops_per_tick=max_k,
                                 config=config,
                                 pipeline_depth=pipeline_depth)
    models = {(e, k): KeyModel(f"{e}/key{k}")
              for e in range(N_ENS) for k in range(N_KEYS)}
    vals = itertools.count(1)
    down = {}  # ens -> peer index currently down
    #: last vsn seen in a write ack per (ens, key) — CAS ops use it
    #: (sometimes deliberately stale)
    vsns = {}

    for _round in range(ROUNDS):
        # -- nemesis: up-mask + membership churn -------------------------
        r = rng.random()
        if r < 0.25 and down:
            # heal a random downed peer
            e = list(down)[int(rng.integers(len(down)))]
            svc.set_peer_up(e, down.pop(e), True)
        elif r < 0.55:
            # kill the CURRENT LEADER of a random ensemble right
            # before the flush that carries this round's ops — the
            # election folds into the same launch (mid-flush kill)
            e = int(rng.integers(N_ENS))
            if e not in down and svc.leader_np[e] >= 0:
                p = int(svc.leader_np[e])
                svc.set_peer_up(e, p, False)
                down[e] = p
        elif r < 0.7:
            # membership churn concurrent with the workload: shrink a
            # random up-and-running ensemble by one member (or restore
            # the full view), keys must survive the joint-consensus
            # transition
            e = int(rng.integers(N_ENS))
            sel = np.zeros((N_ENS,), bool)
            sel[e] = True
            nv = svc.member_np.copy()
            if nv[e].sum() == N_PEERS:
                victim = int(rng.integers(N_PEERS))
                if victim != svc.leader_np[e]:
                    nv[e, victim] = False
            else:
                nv[e] = True
            svc.update_members(sel, nv)

        pending = _submit_batch(rng, svc, models, vals, vsns, seed)

        # -- lease expiry race: sometimes jump virtual time past the
        #    lease before flushing, so leased reads race renewal ------
        if rng.random() < 0.3:
            runtime.run_for(config.lease() * 2.5)
        _drain(svc, runtime, pending)
        _apply_outcomes(pending)

    # -- quiesce + no-data-loss read-back (prop_sc:835-880) -------------
    for e, p in list(down.items()):
        svc.set_peer_up(e, p, True)
    svc.flush()  # fold in any pending elections
    pending = []
    for (e, k), m in models.items():
        pending.append(("get", m, None, svc.kget(e, f"key{k}"), None))
    _drain(svc, runtime, pending)
    _apply_outcomes(pending)  # raises Violation on stale/lost reads

    served = sum(1 for m in models.values()
                 for ev in m.history if ev[0] == "read")
    assert served >= len(models), "quiesced read-back did not complete"
    # Sanity floor, not equality: a round whose ops all resolve
    # pre-flush (absent-key gets/deletes) never launches.
    assert svc.flushes >= ROUNDS // 2


@pytest.mark.parametrize("seed", conftest.soak_seeds([801, 802, 803, 804]))
def test_service_linearizable_across_launch_failures(seed):
    """Device-launch failures (XLA error / dead backend shapes) join
    the nemesis: a seeded ~15% of full_step launches raise, the
    service fails that flush's ops and rolls the engine state + host
    mirrors back, and the surviving history must STILL be
    linearizable — a rollback that resurrected or dropped a committed
    write would surface as a Violation on read-back."""
    from riak_ensemble_tpu.parallel.batched_host import _LocalEngine

    inject_rng = np.random.default_rng(seed + 50_000)
    # The nemesis SCHEDULE guarantees >=1 firing per seed (one launch
    # in the first handful fails deterministically; the rest draw the
    # usual ~15%), so the firing gate below measures the system's
    # rollback behavior, never the dice — a purely random schedule can
    # legitimately draw zero injections on a quiet seed and abort a
    # soak (VERDICT r3 weak #5 / directive #8).
    forced_launch = 1 + int(inject_rng.integers(6))
    launch_no = 0

    class FailingEngine(_LocalEngine):
        def full_step(self, *a, **kw):
            nonlocal launch_no
            launch_no += 1
            if launch_no == forced_launch or inject_rng.random() < 0.15:
                raise RuntimeError("injected-launch-failure")
            return _LocalEngine.full_step(*a, **kw)

    rng = np.random.default_rng(seed)
    runtime = Runtime(seed=seed)
    config = fast_test_config()
    svc = BatchedEnsembleService(runtime, N_ENS, N_PEERS, n_slots=8,
                                 tick=None, max_ops_per_tick=8,
                                 config=config, engine=FailingEngine())
    models = {(e, k): KeyModel(f"{e}/key{k}")
              for e in range(N_ENS) for k in range(N_KEYS)}
    vals = itertools.count(1)
    vsns = {}
    down = {}
    failures = 0

    def bump():
        nonlocal failures
        failures += 1

    def drain(pending):
        _drain(svc, runtime, pending, max_flushes=25,
               tolerate="injected-launch-failure", on_tolerated=bump)

    for _round in range(ROUNDS):
        r = rng.random()
        if r < 0.3 and down:
            e = list(down)[int(rng.integers(len(down)))]
            svc.set_peer_up(e, down.pop(e), True)
        elif r < 0.6:
            e = int(rng.integers(N_ENS))
            if e not in down and svc.leader_np[e] >= 0:
                p = int(svc.leader_np[e])
                svc.set_peer_up(e, p, False)
                down[e] = p

        pending = _submit_batch(rng, svc, models, vals, vsns, seed)

        if rng.random() < 0.3:
            runtime.run_for(config.lease() * 2.5)
        drain(pending)
        _apply_outcomes(pending)

    # quiesce: heal everything, then read back every key — the
    # model raises Violation on any stale/lost/resurrected value.
    for e, p in list(down.items()):
        svc.set_peer_up(e, p, True)
    for _ in range(10):
        try:
            svc.flush()
            break
        except RuntimeError as exc:
            # only the nemesis is survivable; a genuine service bug
            # raising here must fail the test, not count as a firing
            assert "injected-launch-failure" in str(exc)
            failures += 1
    pending = [("get", m, None, svc.kget(e, f"key{k}"), None)
               for (e, k), m in models.items()]
    drain(pending)
    _apply_outcomes(pending)
    # The schedule forces >=1 injection, so zero observed firings now
    # means a firing was swallowed somewhere (a real harness bug), not
    # an unlucky seed.
    assert failures > 0, "scheduled nemesis firing was not observed"


@pytest.mark.parametrize("seed", conftest.soak_seeds([901, 902, 903, 904]))
def test_service_linearizable_under_corruption_nemesis(seed):
    """Device-state corruption joins the nemesis (VERDICT r3 #9): the
    sweep flips object/tree-leaf/tree-node lanes on a minority of
    replicas MID-RUN — concurrent with client load, leader kills and
    lease races — and the history must stay linearizable: the
    integrity gate excludes damaged replicas from read quorums
    (get_latest_obj's hash extra-check), reads heal accessed slots,
    detection triggers the exchange, and no corrupted copy is ever
    served.  Matches test/sc.erl postconditions (:835-880) under the
    corrupt_* scenario family.
    """
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    rng = np.random.default_rng(seed)
    runtime = Runtime(seed=seed)
    config = fast_test_config()
    svc = BatchedEnsembleService(runtime, N_ENS, N_PEERS, n_slots=8,
                                 tick=None, max_ops_per_tick=8,
                                 config=config)
    models = {(e, k): KeyModel(f"{e}/key{k}")
              for e in range(N_ENS) for k in range(N_KEYS)}
    vals = itertools.count(1)
    vsns = {}
    down = {}
    corruptions_injected = 0

    def corrupt_lane():
        """Flip one replica lane.  Only peers {2, 3} are targets — at
        most 2 of 5 copies, always a minority, so a hash-valid holder
        of every committed object survives by construction (the
        engine refuses to bless slots with no valid copy; an
        all-copies nemesis would be unrecoverable by design)."""
        nonlocal corruptions_injected
        e = int(rng.integers(N_ENS))
        p = int(rng.integers(2, 4))
        s = int(rng.integers(svc.n_slots))
        mode = int(rng.integers(3))
        st = svc.state
        if mode == 0:    # object plane: value diverges from its leaf
            st = st._replace(obj_val=st.obj_val.at[e, p, s].set(
                int(rng.integers(1 << 20, 1 << 21))))
        elif mode == 1:  # leaf lane: hash no longer vouches for obj
            st = st._replace(tree_leaf=st.tree_leaf.at[e, p, s, :].set(
                jnp.uint32(0xDEADBEEF)))
        else:            # upper tree node: path verification fails
            u = int(rng.integers(st.tree_node.shape[2]))
            st = st._replace(tree_node=st.tree_node.at[e, p, u, :].set(
                jnp.uint32(0xBADBAD)))
        svc.state = st
        corruptions_injected += 1

    for _round in range(ROUNDS):
        r = rng.random()
        if r < 0.2 and down:
            e = list(down)[int(rng.integers(len(down)))]
            svc.set_peer_up(e, down.pop(e), True)
        elif r < 0.45:
            e = int(rng.integers(N_ENS))
            if e not in down and svc.leader_np[e] >= 0:
                p = int(svc.leader_np[e])
                if p not in (2, 3):   # keep corruption targets up:
                    svc.set_peer_up(e, p, False)   # down+corrupt on
                    down[e] = p       # the same copy would stack the
                                      # two nemeses past a minority
        elif r < 0.8:
            corrupt_lane()

        pending = _submit_batch(rng, svc, models, vals, vsns, seed)
        if rng.random() < 0.3:
            runtime.run_for(config.lease() * 2.5)
        _drain(svc, runtime, pending)
        _apply_outcomes(pending)

    assert corruptions_injected > 0, "corruption arm never fired"
    assert svc.corruptions > 0, \
        "no injected corruption was ever DETECTED in-round"

    # quiesce + scrub: heal peers, run the anti-entropy sweep over
    # every ensemble (the host-driven scrub the exchange flow serves),
    # then the read-back must see every acked value and the trees must
    # verify clean — healed, not blessed.
    for e, p in list(down.items()):
        svc.set_peer_up(e, p, True)
    svc.flush()
    svc.state, diverged, synced = svc.engine.exchange_step(
        svc.state, jnp.ones((N_ENS,), bool), jnp.asarray(svc.up))
    assert bool(np.asarray(synced).all())
    pending = [("get", m, None, svc.kget(e, f"key{k}"), None)
               for (e, k), m in models.items()]
    _drain(svc, runtime, pending)
    _apply_outcomes(pending)   # Violation on stale/lost reads

    node_bad, leaf_bad = eng.verify_trees(svc.state)
    # Damaged lanes on SLOTS THAT NEVER HELD DATA can survive the
    # scrub (no valid winner exists to adopt; the engine refuses to
    # bless them) — but every lane carrying committed data must have
    # healed.  Re-verify only slots with objects: leaf corruption on
    # empty slots is the one acceptable residue.
    obj_exists = np.asarray(svc.state.obj_seq) > 0      # [E, M, S]
    leaf_ok = np.asarray(
        eng.hashk.obj_leaf_hash(svc.state.obj_epoch, svc.state.obj_seq,
                                svc.state.obj_val)
        == svc.state.tree_leaf).all(-1)
    assert (leaf_ok | ~obj_exists).all(), "committed data not healed"
