"""Multi-process SPMD: the 'ens' axis spanning OS processes.

The reference's core premise is peers on machines with independent
failure domains over disterl (``riak_ensemble_msg.erl:132-142``).  The
TPU-native analog (ARCHITECTURE §7): ``jax.distributed`` + a global
mesh whose 'ens' dim spans processes/hosts; every process runs the
SAME engine launch sequence (single-program, multiple-data), ensembles
never need cross-process collectives, and each host's service shard
owns its local ensembles.

This test runs that story for real: two OS processes × 4 virtual CPU
devices each form one 8-device global mesh; both execute the full
protocol sequence (elections → K/V → failover → joint-consensus
reconfig → reads) through ``ShardedEngine`` over the global mesh, and
every process checks its ADDRESSABLE shards bit-for-bit against an
unsharded single-process oracle of the same scenario.  A second phase
runs one independent ``BatchedEnsembleService`` per process over its
ensemble shard — the documented multi-host service deployment shape.
"""

import os
import socket
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); coord = sys.argv[2]
try:
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=2, process_id=pid)
except Exception as exc:
    print("SKIP:", exc); raise SystemExit(0)

# Capability probe: initialize() succeeding does NOT mean the backend
# can EXECUTE cross-process computations — jaxlib's CPU collectives
# need a Gloo/MPI client, and without one the first sharded
# device_put dies mid-scenario with "Multiprocess computations
# aren't implemented on the CPU backend".  Probe with one tiny
# cross-process broadcast and convert that environment limitation
# into the deterministic SKIP the parent understands.
try:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("retpu-mp-probe")
except Exception as exc:
    print("SKIP: multiprocess collectives unavailable on this "
          "backend:", exc)
    raise SystemExit(0)

assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.parallel import distributed
from riak_ensemble_tpu.parallel.mesh import ShardedEngine

mesh = distributed.global_mesh(n_peer=1)
assert dict(mesh.shape) == {{"ens": 8, "peer": 1}}, mesh.shape
se = ShardedEngine(mesh)

E, M, S, K = 16, 3, 8, 4

def put(x, spec):
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

# Deterministic scenario inputs (identical in every process).
rng = np.random.default_rng(7)
kind = rng.choice([eng.OP_PUT, eng.OP_GET], (K, E)).astype(np.int32)
slot = rng.integers(0, S, (K, E)).astype(np.int32)
val = rng.integers(1, 1 << 20, (K, E)).astype(np.int32)
lease = np.zeros((K, E), bool)
up0 = np.ones((E, M), bool)
up1 = up0.copy(); up1[:, 0] = False        # peer 0 dies everywhere
elect = np.ones((E,), bool)
cand0 = np.zeros((E,), np.int32)
cand1 = np.ones((E,), np.int32)            # failover to peer 1
shrink = np.ones((E, M), bool); shrink[:, 0] = False
noprop = np.zeros((E,), bool)

def scenario(engine, state, place):
    out = {{}}
    state, won = engine.elect_step(state, place(elect, P("ens")),
                                   place(cand0, P("ens")),
                                   place(up0, P("ens", "peer")))
    out["won0"] = won
    state, res = engine.kv_step_scan(
        state, place(kind, P(None, "ens")), place(slot, P(None, "ens")),
        place(val, P(None, "ens")), place(lease, P(None, "ens")),
        place(up0, P("ens", "peer")))
    out["committed"] = res.committed
    state, won = engine.elect_step(state, place(elect, P("ens")),
                                   place(cand1, P("ens")),
                                   place(up1, P("ens", "peer")))
    out["won1"] = won
    state, inst, _ = engine.reconfig_step(
        state, place(elect, P("ens")), place(shrink, P("ens", "peer")),
        place(up1, P("ens", "peer")))
    state, _, coll = engine.reconfig_step(
        state, place(noprop, P("ens")), place(shrink, P("ens", "peer")),
        place(up1, P("ens", "peer")))
    out["installed"], out["collapsed"] = inst, coll
    gk = np.full((K, E), eng.OP_GET, np.int32)
    state, res = engine.kv_step_scan(
        state, place(gk, P(None, "ens")), place(slot, P(None, "ens")),
        place(np.zeros((K, E), np.int32), P(None, "ens")),
        place(lease, P(None, "ens")), place(up1, P("ens", "peer")))
    out["get_ok"], out["value"] = res.get_ok, res.value
    out["epoch"], out["obj_val"] = state.epoch, state.obj_val
    return out

# Sharded run over the cross-process mesh.
sharded = scenario(se, se.init_state(E, M, S), put)

# Unsharded oracle, local devices only.
class _Local:
    elect_step = staticmethod(eng.elect_step)
    kv_step_scan = staticmethod(eng.kv_step_scan)
    reconfig_step = staticmethod(eng.reconfig_step)
oracle = scenario(_Local, eng.init_state(E, M, S),
                  lambda x, spec: jnp.asarray(x))

# Every ADDRESSABLE shard must equal the oracle slice: the SPMD run
# across processes computes exactly the single-process semantics.
checked = 0
for name in sharded:
    want = np.asarray(oracle[name])
    for sh in sharded[name].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(sh.data), want[sh.index], err_msg=name)
        checked += 1
assert checked > 0
print(f"ENGINE-EQUIV ok ({{checked}} shards checked)")

# Phase 2: the service deployment shape — one independent service per
# process over its ensemble shard (ensembles are independent; client
# traffic routes by ensemble id; no cross-host coordination outside
# the kernels).
from riak_ensemble_tpu.config import fast_test_config
from riak_ensemble_tpu.parallel.batched_host import BatchedEnsembleService
from riak_ensemble_tpu.runtime import Runtime

rt = Runtime(seed=pid)
svc = BatchedEnsembleService(rt, 8, 3, 8, tick=0.005,
                             config=fast_test_config())
futs = [svc.kput(e, "k", b"p%d-e%d" % (pid, e)) for e in range(8)]
for e, f in enumerate(futs):
    assert rt.await_future(f, 10.0)[0] == "ok", (e, f.value)
for e in range(8):
    assert rt.await_future(svc.kget(e, "k"), 10.0) == \
        ("ok", b"p%d-e%d" % (pid, e))
svc.stop()
print("SERVICE-SHARD ok")
print("MPOK")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_spmd_engine_equivalence(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(CHILD.format(repo=REPO))
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if "SKIP:" in out:
            pytest.skip(f"jax.distributed unavailable: {out[-300:]}")
        assert p.returncode == 0, f"proc {i}:\n{out[-3000:]}"
        assert "MPOK" in out, f"proc {i}:\n{out[-3000:]}"
        assert "ENGINE-EQUIV ok" in out
        assert "SERVICE-SHARD ok" in out
