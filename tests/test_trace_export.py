"""Chrome-trace export of span timelines + controller decisions
(tools/trace_export.py, docs/ARCHITECTURE.md §14).

Unit round trip on a canned store, the documented timeline semantics
(per-flush spans sequential, cross-flush ordinal), the flight-dump
CLI path, and the acceptance round trip: a timeline RECORDED on a
live 3-host replication group (leader + two in-process replica
lanes) exports to a JSON every span of which matches the store."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from riak_ensemble_tpu import obs  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    WallRuntime)
from tools import trace_export  # noqa: E402


def _events_by_tid(events):
    out = {}
    for ev in events:
        out.setdefault(ev["tid"], []).append(ev)
    return out


def test_unit_round_trip_canned_store(tmp_path):
    store = obs.SpanStore()
    store.record(7, "leader", [("queue_wait", 0.001),
                               ("device_d2h", 0.004),
                               ("repl_ack", 0.002)], k=4)
    store.record(7, "replica@h:1", [("validate", 0.0005),
                                    ("apply", 0.003)], kind="delta")
    store.record(9, "leader", [("queue_wait", 0.002)])
    decisions = [{"seq": 1, "flush_id": 7, "actuator": "ack_rtt",
                  "cause": "repl_ack_ms_p50", "observed": 5.0,
                  "knob": "pipeline_depth", "old": 1, "new": 2}]
    path = str(tmp_path / "trace.json")
    doc = trace_export.export(path, [7, 9, 12345], decisions,
                              store=store)
    with open(path, encoding="utf-8") as fh:
        loaded = json.load(fh)
    assert loaded == doc  # the written JSON round-trips exactly
    evs = loaded["traceEvents"]
    by_tid = _events_by_tid(evs)
    # every span in the store is an "X" event with its measured
    # duration (microseconds), under its role track
    leader = [e for e in by_tid["leader"] if e["ph"] == "X"]
    assert [(e["name"], e["dur"]) for e in leader
            if e["args"]["flush_id"] == 7] == [
        ("queue_wait", 1000.0), ("device_d2h", 4000.0),
        ("repl_ack", 2000.0)]
    # within a flush the spans stack sequentially from the base
    assert leader[1]["ts"] == leader[0]["ts"] + leader[0]["dur"]
    rep = [e for e in by_tid["replica@h:1"] if e["ph"] == "X"]
    assert [e["name"] for e in rep] == ["validate", "apply"]
    # roles of one flush share the base tick
    assert rep[0]["ts"] == leader[0]["ts"]
    # cross-flush: flush 9 starts after flush 7's widest role ends
    f7 = [e for e in leader if e["args"]["flush_id"] == 7]
    f9 = [e for e in leader if e["args"]["flush_id"] == 9]
    assert f9 and f9[0]["ts"] > f7[-1]["ts"] + f7[-1]["dur"]
    # the controller decision is an instant event on its own track,
    # anchored at its flush's base, carrying the full journal entry
    ctrl = by_tid["controller"]
    assert len(ctrl) == 1 and ctrl[0]["ph"] == "i"
    assert ctrl[0]["ts"] == leader[0]["ts"]
    assert ctrl[0]["args"]["new"] == 2
    # the never-recorded fid contributed nothing (skipped, not fake)
    assert not [e for e in evs
                if e.get("args", {}).get("flush_id") == 12345]


def test_flight_dump_cli_path(tmp_path, capsys):
    dump = {
        "schema": "retpu-flight-dump-v3",
        "ring": [{"flush_id": 3, "t": time.time(), "k": 2,
                  "queue_wait": 0.001, "device_d2h": 0.002,
                  "total": 0.003, "a_width": 8,
                  "payload_bytes": 64, "queued_rounds": 0,
                  "in_flight": 0}],
        "controller_decisions": [
            {"seq": 4, "flush_id": 3, "actuator": "tenant_guard",
             "cause": "tenant_ops_share", "observed": 0.9,
             "knob": "admission_cap[hot]", "old": None, "new": 4}],
    }
    src = tmp_path / "dump.json"
    src.write_text(json.dumps(dump))
    out = tmp_path / "trace.json"
    assert trace_export.main(["--flight-dump", str(src),
                              "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    # marks render as spans (derived/meta fields excluded), the
    # journal entry as an autotune instant
    assert {"queue_wait", "device_d2h",
            "autotune admission_cap[hot]"} <= names
    assert "total" not in names  # META field, not a span
    assert doc["otherData"]["source_dump_schema"] \
        == "retpu-flight-dump-v3"


def test_live_3host_recorded_timeline_round_trip(tmp_path):
    """Acceptance: record a real 3-host flush timeline (leader + two
    in-process replica lanes over the delta wire), export it, and
    verify every exported span matches the store's record — the
    tool renders what the obs plane measured, nothing else."""
    from riak_ensemble_tpu.parallel import repgroup

    before = set(obs.SPANS.flush_ids())
    servers = [repgroup.ReplicaServer(4, 3, 8,
                                      data_dir=str(tmp_path / f"r{i}"),
                                      config=fast_test_config())
               for i in (1, 2)]
    svc = repgroup.ReplicatedService(
        WallRuntime(), 4, 1, 8, group_size=3,
        peers=[("127.0.0.1", s.repl_port) for s in servers],
        ack_timeout=30.0, max_ops_per_tick=4,
        config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    try:
        repgroup.warmup_kernels(svc)
        assert svc.takeover()
        futs = [svc.kput_many(e, ["a", "b"], [b"1", b"2"])
                for e in range(4)]
        while any(svc.queues):
            svc.flush()
        svc._drain_pending(block_all=True)
        assert all(f.done for f in futs)
        # a journaled decision to ride along (the journal is data
        # here; actuation is exercised in test_controller)
        fids = [f for f in obs.SPANS.flush_ids() if f not in before]
        assert fids
        ev = svc.controller.journal.note(
            "ack_rtt", "repl_ack_ms_p50", 5.0,
            knob="pipeline_depth", old=1, new=2, flush_id=fids[-1])
        path = str(tmp_path / "trace.json")
        doc = trace_export.export(
            path, fids, svc.controller.journal.snapshot())
        loaded = json.loads(open(path, encoding="utf-8").read())
        assert loaded == doc
        evs = loaded["traceEvents"]
        spans = [e for e in evs if e["ph"] == "X"]
        assert spans, "no spans exported from a live run"
        # ROUND TRIP: every exported span re-finds its (name,
        # duration) in the store's timeline for its flush and role
        for e in spans:
            tl = obs.timeline(e["args"]["flush_id"])
            assert tl is not None
            side = tl[e["tid"]]
            match = [d for n, d in side["spans"]
                     if n == e["name"]
                     and abs(d * 1e6 - e["dur"]) < 0.5]
            assert match, (e, side["spans"])
        # at least one flush exported both leader and a lane-tagged
        # replica side (the 3-host join, not a leader-only render)
        by_fid = {}
        for e in spans:
            by_fid.setdefault(e["args"]["flush_id"],
                              set()).add(e["tid"])
        assert any("leader" in roles
                   and any(t.startswith("replica") for t in roles)
                   for roles in by_fid.values()), by_fid
        # the decision instant rode along with its journal payload
        ctrl = [e for e in evs if e["tid"] == "controller"]
        assert len(ctrl) == 1
        assert ctrl[0]["args"]["seq"] == ev["seq"]
    finally:
        svc.stop()
        for s in servers:
            s.stop()
