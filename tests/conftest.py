"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import (pytest loads conftest first).  Bench and
production run on real TPU; tests exercise the multi-chip sharding paths
on virtual CPU devices per the driver contract.
"""

import os
import sys

# Override (not setdefault): the driver environment pins JAX_PLATFORMS
# to the real TPU tunnel, but the test contract is the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Make the repo root importable regardless of pytest invocation dir.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's sitecustomize registers the real-TPU plugin and
# forces jax_platforms at interpreter start; backends initialize
# lazily, so re-pin to CPU here (before any device use).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: long nemesis sweeps/soaks carry
    # the slow marker and run only in the soak lane — register it so
    # -W error environments don't trip on an unknown marker
    config.addinivalue_line(
        "markers", "slow: long-running nemesis sweeps/soaks excluded "
        "from the tier-1 window (run explicitly or via -m slow)")
    # The mesh equivalence suite needs the forced 8-device CPU mesh
    # (set above for every test session).  The marker lets CI run it
    # as its OWN pytest session (`pytest -m mesh`) so a future change
    # to the forced device count can't silently contaminate the other
    # suites — and lets a single-device environment deselect it.
    config.addinivalue_line(
        "markers", "mesh: single-shard↔mesh equivalence suite; needs "
        "xla_force_host_platform_device_count=8 (runs standalone via "
        "-m mesh)")


def soak_seeds(base):
    """CI runs the fixed seed list; soak sweeps widen it via
    RETPU_SOAK_SEEDS="start:count" (fresh seeds, not repeats) so
    long-running nemesis soaks measure new schedules every run."""
    spec = os.environ.get("RETPU_SOAK_SEEDS")
    if not spec:
        return base
    start, count = (int(x) for x in spec.split(":"))
    return list(range(start, start + count))
