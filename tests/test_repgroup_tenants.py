"""Dynamic tenants on replication groups — multi-tenancy composed
with host-fault tolerance: create/destroy ride the group's
(epoch, seq) stream with the same host-quorum barrier as writes, the
tenant directory survives leader death (snapshot installs carry it),
and the consensus-managed reconciler can place tenants on a
replication-group owner."""

import signal
import time

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import service_directory as sd  # noqa: E402
from riak_ensemble_tpu import service_manager as sm  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import WallRuntime  # noqa: E402
from riak_ensemble_tpu.testing import ManagedCluster  # noqa: E402

from test_repgroup import (  # noqa: E402
    GROUP, N_ENS, N_SLOTS, _control, _restart, _settle,
    _spawn_replica, _wait_synced)


def _dyn_group(tmp_path, procs, dirs):
    for name in ("r1", "r2"):
        dirs[name] = str(tmp_path / name)
        procs[name] = _spawn_replica(dirs[name], extra=["--dynamic"])
    svc = repgroup.ReplicatedService(
        WallRuntime(), N_ENS, 1, N_SLOTS, group_size=GROUP,
        peers=[("127.0.0.1", procs[n][1]) for n in ("r1", "r2")],
        ack_timeout=60.0, config=fast_test_config(), dynamic=True,
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover()
    return svc


def test_replicated_lifecycle_and_directory_survives_leader_death(
        tmp_path):
    import asyncio

    from riak_ensemble_tpu import svcnode

    procs = {}
    dirs = {}
    try:
        svc = _dyn_group(tmp_path, procs, dirs)

        # replicated create: quorum-barriered, deterministic rows
        orders = svc.create_ensemble("orders")
        billing = svc.create_ensemble("billing")
        assert orders is not None and billing is not None
        assert svc.create_ensemble("orders") is None  # name taken
        r = _settle(svc, [svc.kput(orders, "k", b"ord"),
                          svc.kput(billing, "k", b"bil")])
        assert all(x[0] == "ok" for x in r)

        # replicated destroy + row recycling across the group
        assert svc.destroy_ensemble("billing")
        billing2 = svc.create_ensemble("billing2")
        assert billing2 is not None
        r = _settle(svc, [svc.kput(billing2, "k", b"bil2")])
        assert r[0][0] == "ok"

        # a killed replica restarts and re-syncs a snapshot that
        # CARRIES the tenant directory
        p1, _, _ = procs["r1"]
        p1.send_signal(signal.SIGKILL)
        p1.wait()
        _restart(procs, dirs, "r1")
        _wait_synced(svc, 2)

        # leader dies; promote r1 — every lifecycle outcome must be
        # visible through the replica's own directory
        svc.stop()
        _, r1_repl, r1_client = procs["r1"]
        _, r2_repl, _ = procs["r2"]
        resp = _control(r1_repl, ("promote",
                                  [("127.0.0.1", r2_repl)]),
                        timeout=300.0)
        assert resp[0] == "ok", resp

        async def check():
            c = svcnode.ServiceClient("127.0.0.1", r1_client)
            await c.connect()
            r = await c.call("resolve_ensemble", "orders",
                             timeout=120.0)
            assert r == ("ok", orders), r
            assert await c.kget(orders, "k", timeout=120.0) == \
                ("ok", b"ord")
            r = await c.call("resolve_ensemble", "billing",
                             timeout=120.0)
            assert r == ("error", "unknown"), r
            r = await c.call("resolve_ensemble", "billing2",
                             timeout=120.0)
            assert r == ("ok", billing2), r
            assert await c.kget(billing2, "k", timeout=120.0) == \
                ("ok", b"bil2")
            # and the promoted leader can keep doing lifecycle ops
            r = await c.call("create_ensemble", "fresh",
                             timeout=120.0)
            assert r[0] == "ok", r
            await c.close()

        asyncio.run(check())
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


def test_reconciler_places_tenants_on_a_replication_group(tmp_path):
    """The full composition: a consensus-managed tenant (root
    ensemble + gossip) reconciled onto an owner that is itself a
    replication GROUP — multi-tenancy over machine-fault tolerance.
    The reconciler is caller-driven (poll=None) since the group runs
    on wall time."""
    procs = {}
    dirs = {}
    mc = ManagedCluster(seed=8, nodes=("node0",))
    mc.enable("node0")
    try:
        svc = _dyn_group(tmp_path, procs, dirs)
        registry = {}
        rec = sm.ServiceReconciler(mc.runtime, mc.mgr("node0"), svc,
                                   "grp@node0", registry.get,
                                   poll=None)
        registry["grp@node0"] = rec
        r = sd.register_service(mc.mgr("node0"), mc.runtime,
                                "grp@node0", "127.0.0.1", 1,
                                (N_ENS, 1, N_SLOTS))
        assert r == "ok", r
        assert sm.create_tenant(mc.mgr("node0"), mc.runtime,
                                "orders") == "ok"
        deadline = time.monotonic() + 60.0
        while svc.resolve_ensemble("orders") is None:
            mc.runtime.run_for(0.5)
            rec.tick()
            assert time.monotonic() < deadline, \
                "tenant never reconciled onto the group"

        ens = svc.resolve_ensemble("orders")
        r = _settle(svc, [svc.kput(ens, "k", b"v")])
        assert r[0][0] == "ok"
        # replicas carry the tenant too (quorum-barriered lifecycle):
        # the write above could not have acked otherwise
        assert svc.stats()["group"]["quorum_failures"] == 0

        # retire through the root -> reconciler destroys on the group
        assert sm.retire_tenant(mc.mgr("node0"), mc.runtime,
                                "orders") == "ok"
        deadline = time.monotonic() + 60.0
        while svc.resolve_ensemble("orders") is not None:
            mc.runtime.run_for(0.5)
            rec.tick()
            assert time.monotonic() < deadline, \
                "retired tenant never destroyed on the group"
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


@pytest.mark.parametrize("seed", [3101])
def test_repgroup_linearizable_across_leader_failovers(tmp_path, seed):
    """sc.erl under MACHINE churn with no protected roles: a random
    workload rides GroupClient while the nemesis kill -9s and restarts
    ANY host — leaders included — so the history spans automatic
    re-elections, re-syncs and fencing.  Ambiguity discipline: in a
    replication group a 'failed' write is AMBIGUOUS (the batch lost
    its host quorum but applied on the leader's lane, and that lane
    may win the next election by newest-state rank), so it joins the
    plausible set via timeout_write — only ACKED writes pin state,
    and losing one raises Violation."""
    import asyncio

    import numpy as np

    from riak_ensemble_tpu.linearizability import KeyModel
    from riak_ensemble_tpu.types import NOTFOUND

    names = ("r1", "r2", "r3")
    procs = {}
    dirs = {}
    import test_repgroup as tr
    repl_ports = {n: tr._free_port() for n in names}
    client_ports = {n: tr._free_port() for n in names}

    def spawn(name):
        # restarts must preserve ports AND the failover/peer config:
        # tr._restart would drop --auto-failover, leaving the group
        # unable to re-elect after enough churn (review r4)
        others = [f"--peer=127.0.0.1:{repl_ports[o]}"
                  for o in names if o != name]
        return _spawn_replica(
            dirs[name], repl_port=repl_ports[name],
            client_port=client_ports[name],
            extra=["--auto-failover", "3.0"] + others)

    rng = np.random.default_rng(seed)
    models = {}
    vals = iter(range(1, 10_000))

    def model(e, k):
        return models.setdefault((e, k), KeyModel(f"{e}/k{k}"))

    try:
        for n in names:
            dirs[n] = str(tmp_path / n)
            procs[n] = spawn(n)
        hosts = [("127.0.0.1", procs[n][2]) for n in names]

        async def run():
            gc = repgroup.GroupClient(hosts, op_timeout=60.0,
                                      discover_timeout=240.0)
            for rnd in range(10):
                # nemesis: kill or restart ANY host (leader included)
                r = rng.random()
                dead = [n for n in names
                        if procs[n][0].poll() is not None]
                alive = [n for n in names if n not in dead]
                if r < 0.3 and len(alive) > 2:
                    victim = alive[int(rng.integers(len(alive)))]
                    p, _, _ = procs[victim]
                    p.send_signal(signal.SIGKILL)
                    p.wait()
                elif r < 0.6 and dead:
                    procs[dead[0]] = spawn(dead[0])

                for _ in range(4):
                    e = int(rng.integers(N_ENS))
                    k = int(rng.integers(2))
                    m = model(e, k)
                    if rng.random() < 0.6:
                        v = next(vals)
                        op = m.invoke_write(v)
                        res = await gc.kput(e, f"k{k}",
                                            v.to_bytes(4, "big"))
                        if isinstance(res, tuple) and res[0] == "ok":
                            m.ack_write(op)
                        else:
                            m.timeout_write(op)  # ambiguous
                    else:
                        res = await gc.kget(e, f"k{k}")
                        if isinstance(res, tuple) and res[0] == "ok":
                            v = res[1]
                            m.ack_read(v if v is NOTFOUND else
                                       int.from_bytes(v, "big"))

            # quiesce: restart everyone, then read back every key
            for n in names:
                if procs[n][0].poll() is not None:
                    procs[n] = spawn(n)
            for (e, k), m in models.items():
                res = await gc.kget(e, f"k{k}")
                assert isinstance(res, tuple) and res[0] == "ok", res
                v = res[1]
                m.ack_read(v if v is NOTFOUND
                           else int.from_bytes(v, "big"))
            await gc.close()

        asyncio.run(run())
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
