"""expand_test.erl parity: grow 1→3 members, read with read_repair,
survive leader suspension (test/expand_test.erl:8-23).

Exercises the joint-consensus membership pipeline end to end: the
update_members entry (peer.erl:655-672), pending-view gossip to the
manager, manager-driven peer starts (state_changed), the pending→views
transition collapse (peer.erl:751-774), and the read-repair path for
keys written before the expansion (peer.erl:1518-1536).
"""

from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import PeerId


def test_expand_1_to_3():
    mc = ManagedCluster(seed=20)
    mc.ens_start(1)

    r = mc.kput("test", b"test")
    assert r[0] == "ok", r
    assert mc.kget("test")[0] == "ok"

    mc.ens_expand(3)
    mc.wait_stable("root")

    # Should trigger read repair on the freshly-joined members.
    r = mc.kget("test", opts=("read_repair",))
    assert r[0] == "ok" and r[1].value == b"test"

    leader = mc.leader_id("root")
    mc.suspend_peer("root", leader)
    mc.wait_stable("root")

    def readable():
        r = mc.kget("test")
        return r[0] == "ok" and r[1].value == b"test"
    assert mc.runtime.run_until(readable, 60.0, poll=0.2)


def test_read_repair_populates_new_members():
    """After expand + read_repair, new members hold the object locally
    (the repair puts land on followers, peer.erl:1518-1536)."""
    mc = ManagedCluster(seed=21)
    mc.ens_start(1)
    assert mc.kput("rr", b"v")[0] == "ok"
    mc.ens_expand(3)
    mc.wait_stable("root")

    r = mc.kget("rr", opts=("read_repair",))
    assert r[0] == "ok"
    node = mc.node0

    def repaired():
        mc.runtime.run_for(0.05)
        count = 0
        for i in (2, 3):
            p = mc.peer("root", PeerId(i, node))
            if p is not None and "rr" in p.mod.data and \
                    p.mod.data["rr"].value == b"v":
                count += 1
        return count == 2
    assert mc.runtime.run_until(repaired, 30.0, poll=0.1), \
        "read repair never populated new members"
