"""Fault-injection plane unit tests (docs/ARCHITECTURE.md §13):
FaultPlan rule/counter semantics, env-knob parsing, the simulator
Network's directional drop + injected delay, the WAL fsync-delay
hook, and the plane's observability surfaces (gauges, health verb,
flight-dump section).
"""

import time

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import faults, wire  # noqa: E402
from riak_ensemble_tpu.runtime import Actor, Runtime  # noqa: E402


@pytest.fixture(autouse=True)
def _no_global_plan():
    """Every test starts and ends with a disarmed global plane (a
    leaked plan would poison unrelated suites' transports)."""
    faults.clear()
    yield
    faults.clear()


# -- FaultPlan rules + counters ---------------------------------------------


def test_directional_drop_is_one_way():
    p = faults.FaultPlan()
    p.drop("a", "b")
    assert p.should_drop("a", "b")
    assert not p.should_drop("b", "a")  # the other direction delivers
    assert p.dropped_frames == 1  # only the True answer counted
    assert p.link_injected("a", "b")["drops"] == 1
    assert p.link_injected("b", "a")["drops"] == 0


def test_wildcards_and_heal():
    p = faults.FaultPlan()
    p.drop("*", "c")
    p.drop("d", None)  # None = "*"
    assert p.should_drop("anything", "c")
    assert p.should_drop("d", "anywhere")
    assert not p.should_drop("x", "y")
    p.heal()
    assert not p.active()
    assert not p.should_drop("d", "anywhere")
    # counters (the evidence) survive the heal
    assert p.dropped_frames == 2


def test_rtt_jitter_bounds_and_counters():
    p = faults.FaultPlan(seed=3)
    p.set_rtt("a", "b", 4.0, jitter_ms=1.0)
    for _ in range(50):
        d = p.delay_s("a", "b")
        assert 0.003 <= d <= 0.005, d
    assert p.delay_s("b", "a") == 0.0  # one-way rule
    assert p.delayed_frames == 50
    assert 150.0 <= p.delay_injected_ms <= 250.0
    p.set_rtt("a", "b", 0.0)  # zero removes the rule
    assert not p.active()


def test_link_rtt_splits_both_directions():
    p = faults.FaultPlan()
    p.set_link_rtt("a", "b", 10.0)
    assert abs(p.delay_s("a", "b") - 0.005) < 1e-9
    assert abs(p.delay_s("b", "a") - 0.005) < 1e-9


def test_reorder_probability_seeded():
    p = faults.FaultPlan(seed=11)
    p.set_reorder("a", "b", 1.0)
    assert p.should_swap("a", "b")
    assert not p.should_swap("b", "a")
    # should_swap only PROPOSES; a swap counts when the sender
    # actually reorders two queued frames
    assert p.reordered_frames == 0
    p.count_reorder("a", "b")
    assert p.reordered_frames == 1
    assert p.link_injected("a", "b")["reorders"] == 1
    p.set_reorder("a", "b", 0.0)
    assert not p.should_swap("a", "b")


def test_describe_is_wire_encodable_plain_data():
    p = faults.FaultPlan(seed=5)
    p.drop("a", "b")
    p.set_rtt("*", "c", 2.5, 0.5)
    p.set_reorder("a", "b", 0.25)
    p.set_fsync_delay(3.0)
    d = p.describe()
    assert d["active"] and d["drop"] == ["a>b"]
    assert d["rtt_ms"] == {"*>c": [2.5, 0.5]}
    assert d["fsync_ms"] == 3.0
    # the health verb ships this through the restricted codec
    assert wire.decode(wire.encode(d)) == d


# -- env knobs ---------------------------------------------------------------


def test_from_env_full_parse():
    env = {
        "RETPU_FAULT_DROP": "a>b, *>c, local>127.0.0.1:9000",
        "RETPU_FAULT_RTT_MS": "local>127.0.0.1:9001=2.5,b>a=1",
        "RETPU_FAULT_RTT_JITTER_MS": "0.5",
        "RETPU_FAULT_REORDER": "0.1",
        "RETPU_FAULT_FSYNC_MS": "3",
        "RETPU_FAULT_SEED": "7",
        "RETPU_FAULT_SILENT": "1",
    }
    p = faults.from_env(env)
    assert p is not None and p.active() and p.silent
    assert p.seed == 7
    assert p.dropping("a", "b") and p.dropping("x", "c")
    assert not p.dropping("b", "a")
    # a host:port DROP destination keeps its port — the README's
    # repgroup form `local>host:port` must target the link label,
    # never eat the port as a numeric suffix
    assert p.dropping("local", "127.0.0.1:9000")
    assert not p.dropping("local", "127.0.0.1")
    # per-link rtt with a host:port destination (the ':' belongs to
    # the address, the trailing number is the value)
    assert p._rtt[("local", "127.0.0.1:9001")] == (2.5, 0.5)
    assert p._rtt[("b", "a")] == (1.0, 0.5)
    assert p._reorder[("*", "*")] == 0.1
    assert p.fsync_ms == 3.0


def test_from_env_global_rtt_and_empty():
    assert faults.from_env({}) is None
    p = faults.from_env({"RETPU_FAULT_RTT_MS": "2"})
    assert p._rtt[("*", "*")] == (2.0, 0.0)


def test_from_env_valueless_per_link_rtt_fails_loudly(capsys):
    """A per-link RTT entry without its ``=ms`` value (e.g. the DROP
    knob's endpoint form pasted into the wrong variable) must fail
    LOUDLY, not silently arm a nemesis that injects nothing — and
    the lazy global arm converts that to a stderr shout + disarm
    rather than killing the first transport thread that asks."""
    with pytest.raises(ValueError, match="needs a trailing =value"):
        faults.from_env({"RETPU_FAULT_RTT_MS": "local>127.0.0.1:9000"})
    import os
    os.environ["RETPU_FAULT_RTT_MS"] = "local>127.0.0.1:9000"
    try:
        faults._armed = False
        faults._global = None
        assert faults.plan() is None  # disarmed, not crashed
        assert "malformed fault-injection knobs" in \
            capsys.readouterr().err
    finally:
        del os.environ["RETPU_FAULT_RTT_MS"]
        faults.clear()


def test_install_clear_and_active_plan():
    assert faults.active_plan() is None
    p = faults.install(faults.FaultPlan())
    # armed but rule-less: active_plan still answers None (the hot
    # paths short-circuit on one call)
    assert faults.active_plan() is None
    p.drop("a", "b")
    assert faults.active_plan() is p
    faults.clear()
    assert faults.active_plan() is None


# -- simulator Network integration ------------------------------------------


class _Sink(Actor):
    def __init__(self, runtime, name, node):
        super().__init__(runtime, name, node=node)  # self-registers
        self.got = []

    def handle(self, msg):
        self.got.append((self.runtime.now, msg))


def _two_node_runtime():
    rt = Runtime(seed=0)
    a = _Sink(rt, ("manager", "a"), "a")
    b = _Sink(rt, ("manager", "b"), "b")
    return rt, a, b


def test_sim_network_oneway_partition():
    rt, a, b = _two_node_runtime()
    rt.net.partition_oneway(["a"], ["b"])  # a→b drops, b→a delivers
    rt.net_send("a", ("manager", "b"), "x")
    rt.net_send("b", ("manager", "a"), "y")
    rt.run_for(1.0)
    assert b.got == []
    assert [m for _t, m in a.got] == ["y"]
    rt.net.heal()
    rt.net_send("a", ("manager", "b"), "x2")
    rt.run_for(1.0)
    assert [m for _t, m in b.got] == ["x2"]
    # the evidence survives the heal
    assert rt.net.plan.dropped_frames == 1


def test_sim_network_injected_delay_virtual_time():
    rt, a, b = _two_node_runtime()
    rt.net.fault_plan().set_rtt("a", "b", 50.0)  # 50 ms one way
    t0 = rt.now
    rt.net_send("a", ("manager", "b"), "slow")
    rt.net_send("b", ("manager", "a"), "fast")
    rt.run_for(1.0)
    (tb, _m), = b.got
    (ta, _m2), = a.got
    assert tb - t0 >= 0.050          # injected on top of base latency
    assert ta - t0 < 0.010           # unaffected direction


# -- WAL fsync-delay hook ----------------------------------------------------


def test_wal_fsync_delay_injected_and_counted(tmp_path):
    from riak_ensemble_tpu.parallel.wal import ServiceWAL

    w = ServiceWAL(str(tmp_path / "w"))
    rec = [(("kv", 0, 0), ("k", 1, 1, 1, b"v", False))]
    t0 = time.perf_counter()
    w.log(rec)
    base = time.perf_counter() - t0

    p = faults.install(faults.FaultPlan())
    p.set_fsync_delay(30.0)
    t0 = time.perf_counter()
    w.log(rec)
    slow = time.perf_counter() - t0
    assert slow >= 0.030
    assert slow > base
    assert p.fsync_delays == 1
    assert p.fsync_delay_injected_ms >= 30.0

    faults.clear()
    t0 = time.perf_counter()
    w.log(rec)
    assert time.perf_counter() - t0 < 0.030
    w.close()


def test_wal_sync_hook_is_overridable(tmp_path):
    """A WAL-local hook (programmatic injection without the global
    plane) — the seam the ISSUE names."""
    from riak_ensemble_tpu.parallel.wal import ServiceWAL

    calls = []
    w = ServiceWAL(str(tmp_path / "w"))
    w.sync_hook = lambda: calls.append(1)
    w.log([(("kv", 0, 0), ("k", 1, 1, 1, b"v", False))])
    w.delete([("kv", 0, 0)])
    assert len(calls) == 2
    w.close()


def test_wal_buffer_mode_skips_fsync_hook(tmp_path):
    """Buffer mode has no fsync barrier — the slow-disk nemesis must
    not tax the path that never touches the disk barrier."""
    from riak_ensemble_tpu.parallel.wal import ServiceWAL

    p = faults.install(faults.FaultPlan())
    p.set_fsync_delay(50.0)
    w = ServiceWAL(str(tmp_path / "w"), sync_mode="buffer")
    t0 = time.perf_counter()
    w.log([(("kv", 0, 0), ("k", 1, 1, 1, b"v", False))])
    assert time.perf_counter() - t0 < 0.050
    assert p.fsync_delays == 0
    w.close()


# -- observability surfaces --------------------------------------------------


def test_fault_gauges_health_and_flight_section():
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime)

    svc = BatchedEnsembleService(WallRuntime(), 2, 1, 4, tick=None,
                                 max_ops_per_tick=2)
    try:
        # clean box: gauges registered (zeros), no injected section
        snap = svc.obs_registry.snapshot()
        assert snap["retpu_fault_active"] == 0
        assert snap["retpu_fault_dropped_frames_total"] == 0
        assert "injected" not in svc.health()
        assert svc._flight_extras()["injected_faults"] == {}

        p = faults.install(faults.FaultPlan())
        p.drop("a", "b").set_fsync_delay(1.0)
        p.should_drop("a", "b")
        snap = svc.obs_registry.snapshot()
        assert snap["retpu_fault_active"] == 1
        assert snap["retpu_fault_dropped_frames_total"] == 1
        inj = svc.health()["injected"]
        assert inj["active"] and inj["drop"] == ["a>b"]
        assert svc._flight_extras()["injected_faults"]["active"]

        # healed: gauge drops to 0, counters keep the history
        p.heal()
        snap = svc.obs_registry.snapshot()
        assert snap["retpu_fault_active"] == 0
        assert snap["retpu_fault_dropped_frames_total"] == 1
        assert "injected" not in svc.health()
    finally:
        svc.stop()


def test_netruntime_policy_heal_and_plan_scope():
    """The asyncio runtime's policy: an attached plan wins over the
    global one, and heal() clears its rules."""
    from riak_ensemble_tpu.netruntime import _NetPolicy

    pol = _NetPolicy()
    assert pol.active_plan() is None  # nothing armed anywhere
    g = faults.install(faults.FaultPlan().drop("x", "y"))
    assert pol.active_plan() is g     # falls through to the global
    own = faults.FaultPlan().drop("a", "b")
    pol.plan = own
    assert pol.active_plan() is own   # attached plan wins
    pol.heal()
    assert pol.active_plan() is None  # own rules cleared...
    assert g.active()                 # ...the global plan untouched
