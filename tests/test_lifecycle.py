"""Dynamic ensemble lifecycle on the scale path.

The reference creates/destroys ensembles at runtime through the
manager (``riak_ensemble_manager:create_ensemble``, manager.erl:157-166;
reconciliation :610-641).  The batched service re-designs that for
fixed device arrays: a logical (named) ensemble maps to a physical
row; create resets + re-views a free row, destroy recycles it — the
slot-recycling discipline one level up.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def make_dynamic(n_ens=4, n_peers=3, n_slots=4, **kw):
    runtime = Runtime(seed=31)
    svc = BatchedEnsembleService(runtime, n_ens, n_peers, n_slots,
                                 tick=0.005, config=fast_test_config(),
                                 dynamic=True, **kw)
    return runtime, svc


def settle(runtime, fut, timeout=5.0):
    return runtime.await_future(fut, timeout)


def test_create_serve_destroy_roundtrip():
    runtime, svc = make_dynamic()
    # before any create: every row is free, ops fail fast
    assert settle(runtime, svc.kput(0, "k", b"v")) == "failed"
    assert settle(runtime, svc.kget(0, "k")) == "failed"

    e = svc.create_ensemble("orders")
    assert e is not None
    assert svc.resolve_ensemble("orders") == e
    assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
    assert settle(runtime, svc.kget(e, "k")) == ("ok", b"v")

    assert svc.destroy_ensemble("orders")
    assert svc.resolve_ensemble("orders") is None
    assert settle(runtime, svc.kget(e, "k")) == "failed"
    assert not svc.destroy_ensemble("orders")  # idempotent-ish: unknown
    svc.stop()


def test_recycled_row_serves_fresh_state():
    """A re-created ensemble on a recycled row must not see the old
    tenant's data, and its ballot epoch stays monotone (stragglers of
    the dead tenant can never outrank the new one)."""
    runtime, svc = make_dynamic(n_ens=1)
    e = svc.create_ensemble("a")
    assert settle(runtime, svc.kput(e, "k", b"old"))[0] == "ok"
    epoch_before = int(np.asarray(svc.state.epoch)[e].max())
    assert svc.destroy_ensemble("a")

    e2 = svc.create_ensemble("b")
    assert e2 == e  # single row: recycled
    assert settle(runtime, svc.kget(e2, "k")) == ("ok", NOTFOUND)
    assert settle(runtime, svc.kput(e2, "k", b"new"))[0] == "ok"
    assert settle(runtime, svc.kget(e2, "k")) == ("ok", b"new")
    assert int(np.asarray(svc.state.epoch)[e2].max()) > epoch_before
    assert len(svc.values) == 1  # old tenant's payloads released
    svc.stop()


def test_capacity_backpressure_and_refill():
    runtime, svc = make_dynamic(n_ens=2)
    assert svc.create_ensemble("a") is not None
    assert svc.create_ensemble("b") is not None
    assert svc.create_ensemble("c") is None          # no capacity
    assert svc.create_ensemble("a") is None          # name taken
    assert svc.destroy_ensemble("a")
    assert svc.create_ensemble("c") is not None      # freed row reused
    svc.stop()


def test_create_destroy_under_load():
    """Lifecycle ops interleave with live traffic on other ensembles:
    nothing cross-talks, queued ops on a destroyed ensemble fail
    (request_failed), survivors keep serving."""
    runtime, svc = make_dynamic(n_ens=8, n_slots=8)
    rows = {n: svc.create_ensemble(n) for n in ("a", "b", "c")}
    futs = [svc.kput(rows[n], f"k{i}", b"%s%d" % (n.encode(), i))
            for n in rows for i in range(4)]
    for f in futs:
        assert settle(runtime, f)[0] == "ok"

    # enqueue on b, destroy b BEFORE the flush lands them
    doomed = [svc.kput(rows["b"], f"d{i}", b"x") for i in range(3)]
    assert svc.destroy_ensemble("b")
    for f in doomed:
        assert f.done and f.value == "failed"

    # a and c unaffected; a new ensemble (reusing b's row) serves
    rows["d"] = svc.create_ensemble("d")
    assert rows["d"] == rows["b"]
    for n in ("a", "c"):
        for i in range(4):
            assert settle(runtime, svc.kget(rows[n], f"k{i}")) == \
                ("ok", b"%s%d" % (n.encode(), i))
    assert settle(runtime, svc.kget(rows["d"], "k0")) == ("ok", NOTFOUND)
    assert settle(runtime, svc.kput(rows["d"], "k0", b"d0"))[0] == "ok"
    # membership change on a live dynamic ensemble still works
    nv = np.ones((8, 3), bool)
    nv[:, 2] = False
    sel = np.zeros(8, bool)
    sel[rows["a"]] = True
    assert svc.update_members(sel, nv)[rows["a"]]
    assert settle(runtime, svc.kget(rows["a"], "k1")) == ("ok", b"a1")
    svc.stop()


def test_lifecycle_survives_crash(tmp_path):
    """create/put/destroy/create sequences replay from the WAL: the
    directory, the live tenants' data, and the destroyed tenant's
    ABSENCE all restore."""
    runtime, svc = make_dynamic(data_dir=str(tmp_path / "d"))
    a = svc.create_ensemble("a")
    b = svc.create_ensemble("b")
    assert settle(runtime, svc.kput(a, "k", b"va"))[0] == "ok"
    assert settle(runtime, svc.kput(b, "k", b"vb"))[0] == "ok"
    assert svc.destroy_ensemble("b")
    c = svc.create_ensemble("c")   # recycles b's row
    assert c == b
    assert settle(runtime, svc.kput(c, "k", b"vc"))[0] == "ok"
    svc.stop()
    svc._wal.close()

    rt2 = Runtime(seed=32)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "d"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "d"),
        dynamic=True)
    assert svc2.resolve_ensemble("a") == a
    assert svc2.resolve_ensemble("b") is None
    assert svc2.resolve_ensemble("c") == c
    assert settle(rt2, svc2.kget(a, "k")) == ("ok", b"va")
    assert settle(rt2, svc2.kget(c, "k")) == ("ok", b"vc")
    # the freed/live row accounting survived too
    assert svc2.create_ensemble("d") is not None
    svc2.stop()


def test_svcnode_lifecycle_ops():
    """Remote create/destroy/resolve through the TCP front-end."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    async def scenario():
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config(),
                                     dynamic=True)
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()

        r = await c.create_ensemble("orders")
        assert r[0] == "ok"
        e = r[1]
        assert await c.resolve_ensemble("orders") == ("ok", e)
        assert (await c.kput(e, "k", b"v"))[0] == "ok"
        assert await c.kget(e, "k") == ("ok", b"v")

        # restricted view over the wire
        r = await c.create_ensemble("two", [True, True, False])
        assert r[0] == "ok"

        assert await c.destroy_ensemble("orders") == ("ok",)
        assert (await c.resolve_ensemble("orders"))[0] == "error"
        assert await c.kget(e, "k") == "failed"
        assert (await c.destroy_ensemble("nope"))[0] == "error"

        # lifecycle ops on a STATIC service answer, don't crash
        await c.close()
        await server.stop()

        server2 = await svcnode.serve(2, 3, 4, port=0,
                                      config=fast_test_config())
        c2 = svcnode.ServiceClient(server2.host, server2.port)
        await c2.connect()
        assert (await c2.create_ensemble("x"))[0] == "error"
        assert (await c2.kput(0, "k", b"v"))[0] == "ok"
        await c2.close()
        await server2.stop()

    asyncio.run(scenario())


def test_svcnode_restart_restores_from_data_dir(tmp_path):
    """Operator restart flow: a svcnode re-serving an existing
    data_dir restores the acked state (not an empty service over the
    old WAL)."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    data = str(tmp_path / "d")

    async def first():
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config(),
                                     dynamic=True, data_dir=data)
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        r = await c.create_ensemble("orders")
        e = r[1]
        assert (await c.kput(e, "k", b"v"))[0] == "ok"
        await c.close()
        # crash analog: close the WAL without checkpointing
        server.svc.stop()
        server.svc._wal.close()
        if server._server is not None:
            server._server.close()
            await server._server.wait_closed()
        return e

    async def second(e):
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config(),
                                     dynamic=True, data_dir=data)
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        assert await c.resolve_ensemble("orders") == ("ok", e)
        assert await c.kget(e, "k") == ("ok", b"v")
        await c.close()
        await server.stop()

    e = asyncio.run(first())
    asyncio.run(second(e))


def test_restore_dynamic_flag_mismatch_fails_loudly(tmp_path):
    """The persisted lifecycle mode wins at restore; an explicitly
    contradicting flag is an error, never a silent reinterpretation
    (a static image restored as dynamic would free every row and the
    first create would wipe restored data)."""
    runtime = Runtime(seed=33)
    svc = BatchedEnsembleService(runtime, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "d"))
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    svc.stop()
    svc._wal.close()

    rt2 = Runtime(seed=34)
    with pytest.raises(ValueError):
        BatchedEnsembleService.restore(
            rt2, str(tmp_path / "d"), tick=0.005,
            config=fast_test_config(), data_dir=str(tmp_path / "d"),
            dynamic=True)
    # omitting the flag restores with the persisted mode
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "d"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "d"))
    assert not svc2.dynamic
    assert settle(rt2, svc2.kget(0, "k")) == ("ok", b"v")
    svc2.stop()
