"""Unit tests for the leader↔replica link and catch-up codecs
(ADVICE r5 regressions): idle-socket timeouts must not tear quiet
links down, and a tree-patch's control-plane meta must validate
before — and apply after — everything else.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import wire  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime,
)


def _frame_bytes(value) -> bytes:
    payload = wire.encode(value)
    return struct.Struct(">I").pack(len(payload)) + payload


class _FakeSock:
    """Scripted socket: each entry is bytes to serve, a 'timeout'
    sentinel, or an exception instance to raise."""

    def __init__(self, script):
        self.script = list(script)
        self.buf = b""

    def recv(self, n):
        if not self.buf:
            if not self.script:
                raise ConnectionError("script exhausted")
            item = self.script.pop(0)
            if item == "timeout":
                raise socket.timeout("timed out")
            if isinstance(item, Exception):
                raise item
            if isinstance(item, tuple) and item[0] == "wait":
                # block until the test's gate opens, then serve
                _tag, event, data = item
                event.wait(5.0)
                item = data
            self.buf = item
        out, self.buf = self.buf[:n], self.buf[n:]
        return out


def _make_link():
    # never connects (no server) — we drive _recv_loop directly; the
    # sender thread just idles on its queue
    link = repgroup.PeerLink("127.0.0.1", 1, lambda: 1)
    link.connected = True
    return link


def test_idle_timeout_with_empty_awaiting_keeps_link():
    """ADVICE r5: a 120 s idle-socket timeout on a link with NOTHING
    outstanding is benign — dropping it forced a full re-sync
    reconnect per idle period on quiet links (stepped-down
    ex-leaders, idle leaders)."""
    link = _make_link()
    link.needs_sync = False
    gen = link._gen
    t = repgroup._Ticket()
    gate = threading.Event()
    sock = _FakeSock([
        "timeout",                               # idle: must NOT drop
        # the response arrives only after the test queued its ticket
        ("wait", gate, _frame_bytes(("applied", 1, 1, 0))),
        ConnectionError("closed"),               # end the loop
    ])

    th = threading.Thread(target=link._recv_loop, args=(sock, gen),
                          daemon=True)
    th.start()
    # wait until the loop survived the idle timeout AND re-entered
    # recv (it popped the gated entry — only the terminal error
    # remains scripted), then queue the ticket and let the response
    # through
    deadline = time.monotonic() + 5.0
    while len(sock.script) > 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    with link._alock:
        link._awaiting.append(t)
    gate.set()
    assert t.event.wait(5.0), "response never paired"
    assert t.result == ("applied", 1, 1, 0)
    th.join(5.0)
    # the idle timeout neither dropped nor desynced the link: the
    # final ConnectionError is what tore it down
    assert link.needs_sync  # set by the terminal drop only
    link.close()


def test_idle_timeout_with_overdue_request_drops():
    """A timeout while a response has been outstanding for a full
    IO_TIMEOUT means the peer is wedged — that still drops the link
    (and fails the ticket)."""
    link = _make_link()
    gen = link._gen
    t = repgroup._Ticket()
    t.posted = time.monotonic() - link.IO_TIMEOUT - 1.0  # overdue
    with link._alock:
        link._awaiting.append(t)
    sock = _FakeSock(["timeout"])
    link._recv_loop(sock, gen)
    assert t.event.is_set() and t.result is None
    assert not link.connected and link.needs_sync
    link.close()


def test_idle_timeout_with_fresh_request_keeps_link():
    """A request posted DURING the blocked recv (the closing instant
    of the idle window) is not overdue: the timeout keeps listening
    instead of failing a fresh request against a healthy peer."""
    link = _make_link()
    link.needs_sync = False
    gen = link._gen
    t = repgroup._Ticket()  # posted just now — not overdue
    with link._alock:
        link._awaiting.append(t)
    sock = _FakeSock([
        "timeout",
        _frame_bytes(("applied", 2, 2, 0)),  # the response arrives
        ConnectionError("closed"),
    ])
    link._recv_loop(sock, gen)
    assert t.event.is_set() and t.result == ("applied", 2, 2, 0)
    link.close()


def test_mid_frame_timeout_drops_even_when_idle():
    """A timeout AFTER bytes of a frame arrived desyncs the stream —
    always a drop, idle or not."""
    link = _make_link()
    gen = link._gen
    half_frame = _frame_bytes(("applied", 1, 1, 0))[:3]
    sock = _FakeSock([half_frame, "timeout"])
    link._recv_loop(sock, gen)
    assert not link.connected
    link.close()


def _mk_svc(dynamic=False):
    return BatchedEnsembleService(WallRuntime(), 4, 1, 8, tick=None,
                                  config=fast_test_config(),
                                  dynamic=dynamic)


def test_install_meta_validates_mode_before_mutating():
    """ADVICE r5: a lifecycle-mode mismatch must fail BEFORE the
    leader's control-plane vectors land — a half-applied meta leaves
    the replica campaigning with mixed state."""
    src = _mk_svc(dynamic=True)
    dst = _mk_svc(dynamic=False)
    # make the source's control plane visibly different
    src.create_ensemble("t0")
    meta = repgroup.dump_meta(src)
    assert repgroup.meta_dynamic(meta) is True
    before = dst.state
    with pytest.raises(ValueError, match="lifecycle-mode mismatch"):
        repgroup.install_meta(dst, meta)
    # NOTHING was applied: same state object, untouched mirrors
    assert dst.state is before
    assert not dst.dynamic
    src.stop()
    dst.stop()


def test_handle_tpatch_rejects_mode_mismatch_before_patches():
    """The tpatch handler rejects a mismatched meta before applying
    object patches — the frozen replica stays consistently frozen
    (still nacking at its old position) for the full-install
    fallback."""
    leader = _mk_svc(dynamic=True)
    leader.create_ensemble("t0")
    replica = _mk_svc(dynamic=False)
    core = repgroup.ReplicaCore(replica)
    state_before = replica.state
    patches = [(0, 0, 7, 7, 42, "k", 5, b"x")]
    frame = ("tpatch", 1, 1, (0, 0), repgroup.dump_meta(leader),
             patches)
    with pytest.raises(ValueError, match="lifecycle-mode mismatch"):
        core.handle_tpatch(frame)
    # the object patch did NOT land either
    assert replica.state is state_before
    assert (core.applied_ge, core.applied_seq) == (0, 0)
    leader.stop()
    replica.stop()


def test_ticket_on_done_fires_on_response_and_on_drop():
    """Round 7's shared-condition ack gather hangs off _Ticket.on_done
    — it must fire BOTH when a response pairs and when a connection
    drop fails the outstanding tickets (result None), or a batch
    settle could sleep to its deadline waiting on a dead link."""
    link = _make_link()
    gen = link._gen
    fired = []
    t_ok = repgroup._Ticket(on_done=lambda: fired.append("ok"))
    t_drop = repgroup._Ticket(on_done=lambda: fired.append("drop"))
    with link._alock:
        link._awaiting.append(t_ok)
        link._awaiting.append(t_drop)
    sock = _FakeSock([
        _frame_bytes(("applied", 1, 7, 123)),
        ConnectionError("closed"),
    ])
    link._recv_loop(sock, gen)
    assert t_ok.event.is_set() and t_ok.result == ("applied", 1, 7, 123)
    assert t_drop.event.is_set() and t_drop.result is None
    assert fired == ["ok", "drop"]
    link.close()


def test_ticket_on_done_exception_does_not_break_pairing():
    """A hook that raises must not tear the receive loop (later
    tickets still pair) — _fire swallows it."""
    link = _make_link()
    gen = link._gen

    def boom():
        raise RuntimeError("hook bug")

    t1 = repgroup._Ticket(on_done=boom)
    t2 = repgroup._Ticket()
    with link._alock:
        link._awaiting.append(t1)
        link._awaiting.append(t2)
    sock = _FakeSock([
        _frame_bytes(("applied", 1, 1, 1)),
        _frame_bytes(("applied", 1, 2, 2)),
        ConnectionError("closed"),
    ])
    link._recv_loop(sock, gen)
    assert t1.result == ("applied", 1, 1, 1)
    assert t2.result == ("applied", 1, 2, 2)
    link.close()


# -- fault-injection plane + bounded connect (round 10) ----------------------


from riak_ensemble_tpu import faults  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    faults.clear()
    yield
    faults.clear()


class _StubReplica:
    """Minimal protocol-speaking replica: answers the hello handshake
    and then acks every frame ``("ping", i)`` with
    ``("applied", i, 0, 0)`` — enough wire truth for link-level fault
    tests without a real ReplicaServer."""

    def __init__(self, respond=True):
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.respond = respond
        self.received = []
        self._stop = False
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while not self._stop:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            try:
                if not self.respond:
                    # half-open: the SYN completed but nothing ever
                    # answers (response direction dead) — hold the
                    # socket open until the test tears down
                    while not self._stop:
                        time.sleep(0.02)
                    continue
                hello = repgroup.recv_frame(conn)
                assert hello[0] == "hello"
                repgroup.send_frame(conn, ("helloed", 1, 0, 0))
                while not self._stop:
                    frame = repgroup.recv_frame(conn)
                    self.received.append(frame)
                    repgroup.send_frame(
                        conn, ("applied", int(frame[1]), 0, 0))
            except (ConnectionError, OSError, wire.WireError):
                continue
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self):
        self._stop = True
        try:
            self.srv.close()
        except OSError:
            pass


def test_half_open_connect_fails_within_bounded_timeout(monkeypatch):
    """Satellite: a peer that accepts the SYN but never answers the
    handshake (firewalled response path, SIGSTOP'd accept loop, a
    one-directional partition eating the reply) must fail the send
    within the CONNECT budget — the handshake previously ran under
    IO_TIMEOUT (120 s) and wedged the sender thread for two minutes
    per attempt."""
    stub = _StubReplica(respond=False)
    monkeypatch.setattr(repgroup.PeerLink, "CONNECT_TIMEOUT", 1.0)
    monkeypatch.setattr(repgroup.PeerLink, "RECONNECT_DELAY", 0.01)
    link = repgroup.PeerLink("127.0.0.1", stub.port, lambda: 1)
    try:
        t0 = time.monotonic()
        t = link.post(("ping", 1))
        assert t.event.wait(5.0), \
            "send wedged past the bounded connect timeout"
        assert time.monotonic() - t0 < 4.0
        assert t.result is None
        assert not link.connected and link.drops >= 1
        # the sender thread survived: a second send fails bounded too
        t2 = link.post(("ping", 2))
        assert t2.event.wait(5.0)
        assert t2.result is None
    finally:
        link.close()
        stub.close()


def test_injected_request_drop_fails_fast_and_counts():
    """A directional leader→replica drop blackholes the frame before
    any socket work: the ticket fires unresolved immediately (missed
    ack at injection speed), the link's injected counter advances,
    and link_stats() shows the rule targeting the link."""
    p = faults.install(faults.FaultPlan())
    # port 1: a real connect attempt would fail loudly — the drop
    # check must short-circuit before any socket work
    link = repgroup.PeerLink("127.0.0.1", 1, lambda: 1)
    p.drop(faults.LOCAL, link.label)
    try:
        t = link.post(("ping", 1))
        assert t.event.wait(2.0)
        assert t.result is None
        assert link.injected_drops == 1
        assert link.drops == 0  # no connection failure, an injection
        st = link.link_stats()
        assert st["injected"]["dropping"] is True
        assert st["injected"]["drops"] >= 1
    finally:
        link.close()


def test_injected_response_drop_consumes_ticket_keeps_pairing():
    """Dropping the RETURN direction: the request reaches the replica
    (and is applied there) but its ack vanishes — the ticket resolves
    None (missed ack), the connection survives, and the NEXT frame's
    response pairs correctly (no off-by-one desync)."""
    stub = _StubReplica()
    p = faults.install(faults.FaultPlan())
    link = repgroup.PeerLink("127.0.0.1", stub.port, lambda: 1)
    try:
        p.drop(link.label, faults.LOCAL)
        t1 = link.post(("ping", 1))
        assert t1.event.wait(5.0)
        assert t1.result is None          # ack blackholed...
        deadline = time.monotonic() + 5.0
        while not stub.received and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stub.received, "request never reached the replica"
        assert link.injected_drops == 1
        assert link.connected             # ...but the link is alive
        p.heal()
        t2 = link.post(("ping", 2))
        assert t2.event.wait(5.0)
        assert t2.result == ("applied", 2, 0, 0)  # pairing intact
    finally:
        link.close()
        stub.close()


def test_injected_request_delay_slows_the_send():
    stub = _StubReplica()
    p = faults.install(faults.FaultPlan())
    link = repgroup.PeerLink("127.0.0.1", stub.port, lambda: 1)
    try:
        # connect cleanly first, then arm the delay
        t0 = link.post(("ping", 0))
        assert t0.event.wait(5.0) and t0.result is not None
        p.set_rtt(faults.LOCAL, link.label, 80.0)
        start = time.monotonic()
        t = link.post(("ping", 1))
        assert t.event.wait(5.0)
        assert t.result == ("applied", 1, 0, 0)
        assert time.monotonic() - start >= 0.080
        assert p.delayed_frames >= 1
    finally:
        link.close()
        stub.close()


def test_reorder_swaps_but_pairing_stays_consistent():
    """Injected adjacent-frame swaps change the WIRE order; tickets
    ride their frames, so every response still resolves the ticket of
    the frame it answers (FIFO in actual send order)."""
    stub = _StubReplica()
    p = faults.install(faults.FaultPlan(seed=1))
    link = repgroup.PeerLink("127.0.0.1", stub.port, lambda: 1)
    try:
        t0 = link.post(("ping", 0))     # establish the connection
        assert t0.event.wait(5.0) and t0.result is not None
        p.set_reorder(faults.LOCAL, link.label, 1.0)
        for i in range(1, 41, 2):
            ta = link.post(("ping", i))
            tb = link.post(("ping", i + 1))
            assert ta.event.wait(5.0) and tb.event.wait(5.0)
            assert ta.result == ("applied", i, 0, 0)
            assert tb.result == ("applied", i + 1, 0, 0)
        # with prob 1.0 and 20 rapid pairs, at least one swap really
        # happened (get_nowait found the second frame queued)
        assert p.reordered_frames >= 1
        swapped = any(
            stub.received[j][1] > stub.received[j + 1][1]
            for j in range(len(stub.received) - 1))
        assert swapped, stub.received
    finally:
        link.close()
        stub.close()


def test_drop_logging_rate_limited(monkeypatch, capsys):
    """Satellite: an active nemesis (or a real flapping link) drives
    drops at frame rate; stderr gets at most one summarized line per
    link per LOG_INTERVAL, while the stats counter keeps the truth."""
    monkeypatch.setattr(repgroup.PeerLink, "RECONNECT_DELAY", 0.0)
    link = _make_link()
    for _ in range(10):
        link._drop()
    assert link.drops == 10
    err = capsys.readouterr().err
    lines = [ln for ln in err.splitlines() if "connection dropped" in ln]
    assert len(lines) == 1, err            # first logs, rest suppressed
    assert "(1 drop(s)" in lines[0], lines[0]  # not the full count
    # after the interval passes, ONE more summarized line carries the
    # suppressed count
    link._last_drop_log -= link.LOG_INTERVAL + 1.0
    link._drop()
    err = capsys.readouterr().err
    lines = [ln for ln in err.splitlines() if "connection dropped" in ln]
    assert len(lines) == 1
    assert "(10 drop(s)" in lines[0], lines[0]
    # a deliberate close() is NOT a link failure: the teardown's own
    # socket drop neither counts nor logs
    before = link.drops
    link.close()
    link._drop()
    assert link.drops == before
    assert "connection dropped" not in capsys.readouterr().err
