"""Differential test: the Pallas MXU quorum kernel must agree with the
jnp reference (quorum_met_batch) — which itself is differentially
tested against the scalar msg.erl-semantics oracle — on randomized
vote matrices, joint views, and every required mode.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops.pallas_quorum import quorum_met_pallas  # noqa: E402
from riak_ensemble_tpu.ops.quorum import (  # noqa: E402
    REQUIRED_MODES, quorum_met_batch, views_to_mask,
)


@pytest.mark.parametrize("required", REQUIRED_MODES)
@pytest.mark.parametrize("seed", [0, 1])
def test_pallas_matches_reference(required, seed):
    rng = np.random.default_rng(seed)
    e, m, v = 100, 7, 3
    # random joint views (first always full membership)
    views = [list(range(m))]
    for _ in range(v - 1):
        if rng.random() < 0.5:
            views.append(sorted(rng.choice(m, size=rng.integers(1, m + 1),
                                           replace=False).tolist()))
    mask = jnp.asarray(views_to_mask(views, v, m))

    valid = jnp.asarray(rng.random((e, m)) < 0.45)
    nack = jnp.asarray((rng.random((e, m)) < 0.3)) & ~valid
    self_idx = jnp.asarray(rng.integers(-1, m, (e,)), jnp.int32)

    ref = np.asarray(quorum_met_batch(valid, nack, mask, self_idx,
                                      required=required))
    got = np.asarray(quorum_met_pallas(valid, nack, mask, self_idx,
                                       required=required,
                                       interpret=jax.default_backend()
                                       != "tpu"))
    np.testing.assert_array_equal(got, ref)


def test_pallas_singleton_and_edge_cases():
    # Singleton view: self vote alone meets quorum.
    mask = jnp.asarray(views_to_mask([[0]], 1, 1))
    valid = jnp.zeros((4, 1), bool)
    nack = jnp.zeros((4, 1), bool)
    self_idx = jnp.asarray([0, 0, -1, -1], jnp.int32)
    ref = np.asarray(quorum_met_batch(valid, nack, mask, self_idx))
    got = np.asarray(quorum_met_pallas(valid, nack, mask, self_idx))
    np.testing.assert_array_equal(got, ref)


def test_pallas_block_padding():
    """E not a multiple of the block size exercises the pad/slice."""
    rng = np.random.default_rng(7)
    e, m = 300, 5
    mask = jnp.asarray(views_to_mask([list(range(m))], 1, m))
    valid = jnp.asarray(rng.random((e, m)) < 0.5)
    nack = jnp.asarray((rng.random((e, m)) < 0.2)) & ~valid
    self_idx = jnp.zeros((e,), jnp.int32)
    ref = np.asarray(quorum_met_batch(valid, nack, mask, self_idx))
    got = np.asarray(quorum_met_pallas(valid, nack, mask, self_idx,
                                       block_e=256))
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# Per-ensemble-mask kernel (the engine's quorum path under
# RETPU_PALLAS_QUORUM=1)


@pytest.mark.parametrize("seed", range(4))
def test_epallas_matches_reference(seed):
    from riak_ensemble_tpu.ops.pallas_quorum import quorum_met_epallas

    rng = np.random.default_rng(seed)
    e, v, m = 37, 3, 7
    valid = jnp.asarray(rng.random((e, m)) < 0.55)
    nack = jnp.asarray((rng.random((e, m)) < 0.3)) & ~valid
    mask = rng.random((e, v, m)) < 0.6
    mask[:, 0, :] |= ~mask[:, 0, :].any(-1, keepdims=True)  # view 0 active
    if seed == 2:
        mask[:, 2, :] = False  # padded (inactive) trailing view
    mask = jnp.asarray(mask)

    ref = quorum_met_batch(valid, nack, mask,
                           jnp.full((e,), -1, jnp.int32),
                           required="quorum")
    got = quorum_met_epallas(valid, nack, mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_engine_flag_gated_pallas_equivalence():
    """RETPU_PALLAS_QUORUM=1 must not change any engine result: run a
    full protocol slice (elect, puts/gets with a down peer, reconfig)
    with the flag off and on and compare everything."""
    import jax as _jax

    from riak_ensemble_tpu.ops import engine as eng

    e, m, s, k = 16, 5, 8, 3

    def scenario():
        state = eng.init_state(e, m, s, views=[list(range(m))])
        up = jnp.ones((e, m), bool)
        yes = jnp.ones((e,), bool)
        state, won = eng.elect_step(state, yes,
                                    jnp.zeros((e,), jnp.int32), up)
        kind = jnp.asarray(np.stack([np.full(e, eng.OP_PUT),
                                     np.full(e, eng.OP_PUT),
                                     np.full(e, eng.OP_GET)]), jnp.int32)
        slot = jnp.asarray(np.arange(k * e).reshape(k, e) % s, jnp.int32)
        val = jnp.asarray(1 + np.arange(k * e).reshape(k, e), jnp.int32)
        lease = jnp.ones((k, e), bool)
        up2 = up.at[:, 0].set(False)
        state, res = eng.kv_step_scan(state, kind, slot, val, lease, up2)
        nv = jnp.asarray(np.tile(np.arange(m) < m - 1, (e, 1)))
        state, inst, _ = eng.reconfig_step(state, yes, nv, up2)
        return won, res, inst, state

    try:
        eng.PALLAS_QUORUM = False
        _jax.clear_caches()
        base = scenario()
        eng.PALLAS_QUORUM = True
        _jax.clear_caches()
        flagged = scenario()
    finally:
        eng.PALLAS_QUORUM = False
        _jax.clear_caches()

    for a, b in zip(_jax.tree.leaves(base), _jax.tree.leaves(flagged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quorum_met_wide_pallas_3dim_view_mask():
    """Regression (round-5 ADVICE): the wide Pallas branch of
    engine._quorum_met must accept a 3-dim [E, V, Ml] view_mask with
    W > 1 — broadcasting it per lane — not just a caller-pre-widened
    4-dim mask."""
    import jax as _jax

    from riak_ensemble_tpu.ops import engine as eng

    rng = np.random.default_rng(5)
    e, w, m, v = 9, 3, 5, 2
    ack = jnp.asarray(rng.random((e, w, m)) < 0.6)
    heard = jnp.asarray(np.ones((e, w, m), bool))
    mask = rng.random((e, v, m)) < 0.7
    mask[:, 0, :] |= ~mask[:, 0, :].any(-1, keepdims=True)
    mask3 = jnp.asarray(mask)
    mask4 = jnp.broadcast_to(mask3[:, None], (e, w, v, m))

    try:
        eng.PALLAS_QUORUM = True
        _jax.clear_caches()
        got3 = np.asarray(eng._quorum_met(ack, heard, mask3, None))
        got4 = np.asarray(eng._quorum_met(ack, heard, mask4, None))
        eng.PALLAS_QUORUM = False
        _jax.clear_caches()
        ref = np.asarray(eng._quorum_met(ack, heard, mask4, None))
    finally:
        eng.PALLAS_QUORUM = False
        _jax.clear_caches()
    np.testing.assert_array_equal(got3, ref)
    np.testing.assert_array_equal(got4, ref)


def test_validate_wide_plane():
    """The host-side guard for the wide kernel's conflict-free
    precondition: distinct valid slots pass; a duplicate valid slot in
    one [g, e] row raises; duplicates masked by OP_NOOP are fine."""
    from riak_ensemble_tpu.ops import engine as eng

    g, e, w = 2, 3, 4
    kind = np.full((g, e, w), eng.OP_PUT, np.int32)
    slot = np.tile(np.arange(w, dtype=np.int32), (g, e, 1))
    eng.validate_wide_plane(kind, slot)  # distinct: ok

    bad = slot.copy()
    bad[1, 2, 3] = bad[1, 2, 0]  # duplicate valid slot
    with pytest.raises(ValueError, match="ensemble 2"):
        eng.validate_wide_plane(kind, bad)

    kind2 = kind.copy()
    kind2[1, 2, 3] = eng.OP_NOOP  # same dup but invalid lane: ok
    eng.validate_wide_plane(kind2, bad)
