"""Differential test: the Pallas MXU quorum kernel must agree with the
jnp reference (quorum_met_batch) — which itself is differentially
tested against the scalar msg.erl-semantics oracle — on randomized
vote matrices, joint views, and every required mode.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops.pallas_quorum import quorum_met_pallas  # noqa: E402
from riak_ensemble_tpu.ops.quorum import (  # noqa: E402
    REQUIRED_MODES, quorum_met_batch, views_to_mask,
)


@pytest.mark.parametrize("required", REQUIRED_MODES)
@pytest.mark.parametrize("seed", [0, 1])
def test_pallas_matches_reference(required, seed):
    rng = np.random.default_rng(seed)
    e, m, v = 100, 7, 3
    # random joint views (first always full membership)
    views = [list(range(m))]
    for _ in range(v - 1):
        if rng.random() < 0.5:
            views.append(sorted(rng.choice(m, size=rng.integers(1, m + 1),
                                           replace=False).tolist()))
    mask = jnp.asarray(views_to_mask(views, v, m))

    valid = jnp.asarray(rng.random((e, m)) < 0.45)
    nack = jnp.asarray((rng.random((e, m)) < 0.3)) & ~valid
    self_idx = jnp.asarray(rng.integers(-1, m, (e,)), jnp.int32)

    ref = np.asarray(quorum_met_batch(valid, nack, mask, self_idx,
                                      required=required))
    got = np.asarray(quorum_met_pallas(valid, nack, mask, self_idx,
                                       required=required,
                                       interpret=jax.default_backend()
                                       != "tpu"))
    np.testing.assert_array_equal(got, ref)


def test_pallas_singleton_and_edge_cases():
    # Singleton view: self vote alone meets quorum.
    mask = jnp.asarray(views_to_mask([[0]], 1, 1))
    valid = jnp.zeros((4, 1), bool)
    nack = jnp.zeros((4, 1), bool)
    self_idx = jnp.asarray([0, 0, -1, -1], jnp.int32)
    ref = np.asarray(quorum_met_batch(valid, nack, mask, self_idx))
    got = np.asarray(quorum_met_pallas(valid, nack, mask, self_idx))
    np.testing.assert_array_equal(got, ref)


def test_pallas_block_padding():
    """E not a multiple of the block size exercises the pad/slice."""
    rng = np.random.default_rng(7)
    e, m = 300, 5
    mask = jnp.asarray(views_to_mask([list(range(m))], 1, m))
    valid = jnp.asarray(rng.random((e, m)) < 0.5)
    nack = jnp.asarray((rng.random((e, m)) < 0.2)) & ~valid
    self_idx = jnp.zeros((e,), jnp.int32)
    ref = np.asarray(quorum_met_batch(valid, nack, mask, self_idx))
    got = np.asarray(quorum_met_pallas(valid, nack, mask, self_idx,
                                       block_e=256))
    np.testing.assert_array_equal(got, ref)
