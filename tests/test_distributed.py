"""Multi-host bootstrap glue (`parallel/distributed.py`): the mesh
factory and engine bring-up over "all devices of the job" — exercised
on the virtual 8-device CPU mesh the driver uses, which is exactly the
single-process multi-device case the module documents as needing no
jax.distributed initialization."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.parallel import distributed


@pytest.mark.parametrize("n_peer", [1, 2, 4])
def test_global_mesh_shapes(n_peer):
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = distributed.global_mesh(n_peer=n_peer)
    assert mesh.shape["peer"] == n_peer
    assert mesh.shape["ens"] == jax.device_count() // n_peer
    # 'peer' innermost: one ens row's peer shards are adjacent devices
    # (ICI-neighbor layout on real hardware).
    grid = np.asarray(mesh.devices)
    flat = [d.id for d in grid.reshape(-1)]
    assert flat == sorted(flat)


def test_global_mesh_rejects_indivisible():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    with pytest.raises(AssertionError):
        distributed.global_mesh(n_peer=3)


def test_sharded_engine_serves_over_all_devices():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    se = distributed.sharded_engine(n_peer=2)
    e, m = 8, 4
    state = se.init_state(e, m, 8, views=[list(range(m))])
    up = jnp.ones((e, m), bool)
    state, won = se.elect_step(state, jnp.ones((e,), bool),
                               jnp.zeros((e,), jnp.int32), up)
    kind = jnp.full((2, e), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((2, e), jnp.int32)
    val = jnp.ones((2, e), jnp.int32)
    state, res = se.kv_step_scan(state, kind, slot, val,
                                 jnp.ones((2, e), bool), up)
    assert np.asarray(won).all()
    assert np.asarray(res.committed).all()


def test_initialize_single_process_noop():
    # Single-process: initialize must not raise (no-op contract).
    distributed.initialize()
