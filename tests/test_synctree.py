"""Synctree unit tests: synctree_pure.erl (basic/corrupt/exchange
across backends), synctree_remote.erl (exchange across a process
boundary, counting messages), synctree_path_test.erl (shared M:1
trees), and a synctree_eqc.erl-style reconcile property.
"""

import random

import pytest

from riak_ensemble_tpu.runtime import Future, Runtime
from riak_ensemble_tpu.synctree.backends import DictBackend, FileBackend
from riak_ensemble_tpu.synctree.tree import (
    NONE, Corrupted, SyncTree, compare_gen, compare_gen_streamed,
    local_compare,
)


def h(n: int) -> bytes:
    return n.to_bytes(8, "big")


def build(n: int, backend=None, width=16, segments=16**3) -> SyncTree:
    """synctree_pure:build/2 — insert keys n..1 with value key*10."""
    t = SyncTree(width=width, segments=segments,
                 backend=backend if backend is not None else DictBackend())
    for i in range(n, 0, -1):
        assert t.insert(i, h(i * 10)) is None
    return t


def expected_diff(num: int, diff: int):
    """synctree_pure:expected_diff/2: keys only in the bigger tree."""
    return [(n, (h(n * 10), NONE)) for n in range(num - diff + 1, num + 1)]


BACKENDS = ["dict", "file"]


def make_backend(kind: str, tmp_path, name="t"):
    if kind == "dict":
        return DictBackend()
    return FileBackend(path=str(tmp_path / f"{name}.log"))


# -- test_basic (synctree_pure.erl:28-37) -----------------------------------


@pytest.mark.parametrize("kind", BACKENDS)
def test_basic(kind, tmp_path):
    t = build(100, make_backend(kind, tmp_path))
    assert t.get(42) == h(420)
    assert t.insert(42, h(42)) is None
    assert t.get(42) == h(42)


# -- test_corrupt (synctree_pure.erl:43-54) ---------------------------------


@pytest.mark.parametrize("kind", BACKENDS)
def test_corrupt(kind, tmp_path):
    t = build(10, make_backend(kind, tmp_path))
    assert t.get(4) == h(40)
    t.corrupt(4)
    assert isinstance(t.get(4), Corrupted)
    t.rehash()
    # after rehash the lost leaf is consistent-but-gone (notfound)
    assert t.get(4) is None


# -- test_exchange (synctree_pure.erl:60-68) --------------------------------


@pytest.mark.parametrize("kind", BACKENDS)
def test_exchange(kind, tmp_path):
    num, diff = 50, 10
    t1 = build(num, make_backend(kind, tmp_path, "a"))
    t2 = build(num - diff, make_backend(kind, tmp_path, "b"))
    result = local_compare(t1, t2)
    assert sorted(result) == expected_diff(num, diff)


def test_exchange_identical_trees_zero_diff():
    t1 = build(50)
    t2 = build(50)
    assert t1.top_hash == t2.top_hash
    assert local_compare(t1, t2) == []


# -- persistence: FileBackend reload (the eleveldb role) --------------------


def test_file_backend_reload(tmp_path):
    path = str(tmp_path / "tree.log")
    t = build(30, FileBackend(path=path))
    top = t.top_hash
    t.backend.close()

    t2 = SyncTree(width=16, segments=16**3, backend=FileBackend(path=path))
    assert t2.top_hash == top
    assert t2.get(7) == h(70)
    assert t2.verify()


# -- synctree_remote.erl: exchange across a process boundary ----------------


def test_remote_exchange_message_counts():
    """Compare via message-passing accessor funs; count remote bucket
    fetches — O(width * height * diffs), NOT O(keys)
    (synctree_remote.erl:24-41; SURVEY §5 long-context analog)."""
    num, diff = 10, 4
    local_tree = build(num)
    remote_tree = build(num - diff)
    stats = {"msgs": 0}

    def local(level, bucket):
        fut = Future()
        fut.resolve(local_tree.exchange_get(level, bucket))
        return fut

    def remote(level, bucket):
        stats["msgs"] += 1
        fut = Future()
        fut.resolve(remote_tree.exchange_get(level, bucket))
        return fut

    gen = compare_gen(local_tree.height, local, remote)
    try:
        fut = next(gen)
        while True:
            fut = gen.send(fut.value)
    except StopIteration as stop:
        key_diff = stop.value
    assert sorted(key_diff) == expected_diff(num, diff)
    # cost bound: each level visits at most the differing buckets
    assert stats["msgs"] <= (local_tree.height + 2) * max(diff, 1) * 2


def test_streamed_exchange_round_trips():
    """The level-batched exchange (start_exchange_level streaming)
    makes O(height) remote ROUND TRIPS however many buckets differ."""
    num, diff = 200, 60
    local_tree = build(num)
    remote_tree = build(num - diff)
    stats = {"remote_calls": 0}

    def many_of(tree, count=False):
        def fetch_many(pairs):
            if count:
                stats["remote_calls"] += 1
            fut = Future()
            fut.resolve([tree.exchange_get(lv, b) for lv, b in pairs])
            return fut
        return fetch_many

    gen = compare_gen_streamed(local_tree.height, many_of(local_tree),
                               many_of(remote_tree, count=True))
    try:
        fut = next(gen)
        while True:
            fut = gen.send(fut.value)
    except StopIteration as stop:
        key_diff = stop.value
    assert sorted(key_diff) == expected_diff(num, diff)
    # root + one batch per descended level
    assert stats["remote_calls"] <= local_tree.height + 2


def test_streamed_matches_unbatched():
    for n1, n2 in ((50, 40), (30, 30), (1, 0)):
        t1, t2 = build(n1), build(n2)

        def many_of(tree):
            def fetch_many(pairs):
                fut = Future()
                fut.resolve([tree.exchange_get(lv, b)
                             for lv, b in pairs])
                return fut
            return fetch_many

        gen = compare_gen_streamed(t1.height, many_of(t1), many_of(t2))
        try:
            fut = next(gen)
            while True:
                fut = gen.send(fut.value)
        except StopIteration as stop:
            streamed = sorted(stop.value)
        assert streamed == sorted(local_compare(t1, t2))


# -- synctree_path_test.erl: shared M:1 tree --------------------------------


def test_shared_tree_path():
    """Two peers sharing one synctree via synctree_path (tree_id
    prefixes isolate their hash spaces — backend.erl:97-108,
    synctree_leveldb key layout)."""
    shared = DictBackend()
    ta = SyncTree(tree_id=b"peerA", segments=16**3, backend=shared)
    tb = SyncTree(tree_id=b"peerB", segments=16**3, backend=shared)
    assert ta.insert("k", h(1)) is None
    assert tb.insert("k", h(2)) is None
    assert ta.get("k") == h(1)
    assert tb.get("k") == h(2)


# -- synctree_eqc.erl-style reconcile property ------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_reconcile_property(seed):
    """Random key sets with missing/different partitions: compare must
    return exactly the delta; applying it converges the trees
    (synctree_eqc.erl port of the hashtree EQC property)."""
    rng = random.Random(seed)
    universe = list(range(200))
    common = {k: h(rng.randrange(1 << 30)) for k in universe
              if rng.random() < 0.6}
    only_a = {k: h(rng.randrange(1 << 30)) for k in universe
              if k not in common and rng.random() < 0.5}
    differ = {k for k in common if rng.random() < 0.2}

    ta = SyncTree(segments=16**3)
    tb = SyncTree(segments=16**3)
    expect = {}
    for k, v in common.items():
        assert ta.insert(k, v) is None
        if k in differ:
            v2 = h(int.from_bytes(v, "big") ^ 1)
            assert tb.insert(k, v2) is None
            expect[k] = (v, v2)
        else:
            assert tb.insert(k, v) is None
    for k, v in only_a.items():
        assert ta.insert(k, v) is None
        expect[k] = (v, NONE)

    delta = dict(local_compare(ta, tb))
    assert delta == expect

    # reconcile: push a's authoritative values into b
    for k, (va, _vb) in delta.items():
        assert tb.insert(k, va) is None
    assert ta.top_hash == tb.top_hash
    assert local_compare(ta, tb) == []
