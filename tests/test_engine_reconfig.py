"""Device-side joint-consensus reconfiguration (BASELINE ladder #5:
replace_members analog at engine scale).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import engine as eng  # noqa: E402


def _elected(e=8, m=5, s=8):
    state = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())
    return state, up


def test_install_then_collapse():
    e, m = 8, 5
    state, up = _elected(e, m)
    # replace members 3,4 with nobody: shrink view to {0,1,2}
    new_view = jnp.asarray(np.tile([True, True, True, False, False],
                                   (e, 1)))
    state, installed, collapsed = eng.reconfig_step(
        state, jnp.ones((e,), bool), new_view, up)
    assert bool(np.asarray(installed).all())
    assert not bool(np.asarray(collapsed).any())
    vm = np.asarray(state.view_mask)
    assert vm[:, 0, :3].all() and not vm[:, 0, 3:].any()
    assert vm[:, 1, :].all()  # old full view retained (joint)

    # While joint, puts need majority in BOTH views.
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((e,), jnp.int32)
    val = jnp.full((e,), 7, jnp.int32)
    lease = jnp.ones((e,), bool)
    # Drop peers 1,2: old view still has 3/5, but new view only 1/3 →
    # joint quorum must FAIL.
    up_partial = jnp.asarray(np.tile([True, False, False, True, True],
                                     (e, 1)))
    _, res = eng.kv_step(state, kind, slot, val, lease, up_partial)
    assert not bool(np.asarray(res.committed).any())
    # All up: commits work while joint.
    state, res = eng.kv_step(state, kind, slot, val, lease, up)
    assert bool(np.asarray(res.committed).all())

    # Collapse to the new view.
    state, installed, collapsed = eng.reconfig_step(
        state, jnp.zeros((e,), bool), new_view, up)
    assert bool(np.asarray(collapsed).all())
    vm = np.asarray(state.view_mask)
    assert not vm[:, 1, :].any()
    # Now quorum is 2-of-3 over {0,1,2}: peers 3,4 down is fine.
    up_new = jnp.asarray(np.tile([True, True, True, False, False],
                                 (e, 1)))
    state, res = eng.kv_step(state, kind, slot, val, lease, up_new)
    assert bool(np.asarray(res.committed).all())


def test_install_requires_commit_quorum():
    e, m = 4, 5
    state, up = _elected(e, m)
    new_view = jnp.asarray(np.tile([True, True, True, False, False],
                                   (e, 1)))
    # Majority down: the try_commit gate fails, no install.
    up_minor = jnp.asarray(np.tile([True, True, False, False, False],
                                   (e, 1)))
    state2, installed, _ = eng.reconfig_step(
        state, jnp.ones((e,), bool), new_view, up_minor)
    assert not bool(np.asarray(installed).any())
    np.testing.assert_array_equal(np.asarray(state2.view_mask),
                                  np.asarray(state.view_mask))


def test_churn_cycle_at_scale():
    """10k ensembles through install→collapse cycles with rolling
    member replacement — the reconfig-under-churn scenario."""
    e, m = 10_000, 5
    state, up = _elected(e, m, s=4)
    rng = np.random.default_rng(0)
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((e,), jnp.int32)
    lease = jnp.ones((e,), bool)
    for round_i in range(3):
        keep = np.ones((e, m), bool)
        drop = rng.integers(0, m, e)
        keep[np.arange(e), drop] = False  # rotate one member out
        new_view = jnp.asarray(keep)
        state, installed, _ = eng.reconfig_step(
            state, jnp.ones((e,), bool), new_view, up)
        assert bool(np.asarray(installed).all()), round_i
        # write while joint
        state, res = eng.kv_step(state, kind, slot,
                                 jnp.full((e,), round_i + 1, jnp.int32),
                                 lease, up)
        assert bool(np.asarray(res.committed).all()), round_i
        state, _, collapsed = eng.reconfig_step(
            state, jnp.zeros((e,), bool), new_view, up)
        assert bool(np.asarray(collapsed).all()), round_i
        # restore full membership for the next cycle
        full = jnp.asarray(np.ones((e, m), bool))
        state, installed, _ = eng.reconfig_step(
            state, jnp.ones((e,), bool), full, up)
        assert bool(np.asarray(installed).all()), round_i
        state, _, collapsed = eng.reconfig_step(
            state, jnp.zeros((e,), bool), full, up)
        assert bool(np.asarray(collapsed).all()), round_i
