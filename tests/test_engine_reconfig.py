"""Device-side joint-consensus reconfiguration (BASELINE ladder #5:
replace_members analog at engine scale).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import engine as eng  # noqa: E402


def _elected(e=8, m=5, s=8):
    state = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())
    return state, up


def test_install_then_collapse():
    e, m = 8, 5
    state, up = _elected(e, m)
    # replace members 3,4 with nobody: shrink view to {0,1,2}
    new_view = jnp.asarray(np.tile([True, True, True, False, False],
                                   (e, 1)))
    state, installed, collapsed = eng.reconfig_step(
        state, jnp.ones((e,), bool), new_view, up)
    assert bool(np.asarray(installed).all())
    assert not bool(np.asarray(collapsed).any())
    vm = np.asarray(state.view_mask)
    assert vm[:, 0, :3].all() and not vm[:, 0, 3:].any()
    assert vm[:, 1, :].all()  # old full view retained (joint)

    # While joint, puts need majority in BOTH views.
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((e,), jnp.int32)
    val = jnp.full((e,), 7, jnp.int32)
    lease = jnp.ones((e,), bool)
    # Drop peers 1,2: old view still has 3/5, but new view only 1/3 →
    # joint quorum must FAIL.
    up_partial = jnp.asarray(np.tile([True, False, False, True, True],
                                     (e, 1)))
    _, res = eng.kv_step(state, kind, slot, val, lease, up_partial)
    assert not bool(np.asarray(res.committed).any())
    # All up: commits work while joint.
    state, res = eng.kv_step(state, kind, slot, val, lease, up)
    assert bool(np.asarray(res.committed).all())

    # Collapse to the new view.
    state, installed, collapsed = eng.reconfig_step(
        state, jnp.zeros((e,), bool), new_view, up)
    assert bool(np.asarray(collapsed).all())
    vm = np.asarray(state.view_mask)
    assert not vm[:, 1, :].any()
    # Now quorum is 2-of-3 over {0,1,2}: peers 3,4 down is fine.
    up_new = jnp.asarray(np.tile([True, True, True, False, False],
                                 (e, 1)))
    state, res = eng.kv_step(state, kind, slot, val, lease, up_new)
    assert bool(np.asarray(res.committed).all())


def test_install_requires_commit_quorum():
    e, m = 4, 5
    state, up = _elected(e, m)
    new_view = jnp.asarray(np.tile([True, True, True, False, False],
                                   (e, 1)))
    # Majority down: the try_commit gate fails, no install.
    up_minor = jnp.asarray(np.tile([True, True, False, False, False],
                                   (e, 1)))
    state2, installed, _ = eng.reconfig_step(
        state, jnp.ones((e,), bool), new_view, up_minor)
    assert not bool(np.asarray(installed).any())
    np.testing.assert_array_equal(np.asarray(state2.view_mask),
                                  np.asarray(state.view_mask))


def test_churn_cycle_at_scale():
    """10k ensembles through install→collapse cycles with rolling
    member replacement — the reconfig-under-churn scenario."""
    e, m = 10_000, 5
    state, up = _elected(e, m, s=4)
    rng = np.random.default_rng(0)
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((e,), jnp.int32)
    lease = jnp.ones((e,), bool)
    for round_i in range(3):
        keep = np.ones((e, m), bool)
        drop = rng.integers(0, m, e)
        keep[np.arange(e), drop] = False  # rotate one member out
        new_view = jnp.asarray(keep)
        state, installed, _ = eng.reconfig_step(
            state, jnp.ones((e,), bool), new_view, up)
        assert bool(np.asarray(installed).all()), round_i
        # write while joint
        state, res = eng.kv_step(state, kind, slot,
                                 jnp.full((e,), round_i + 1, jnp.int32),
                                 lease, up)
        assert bool(np.asarray(res.committed).all()), round_i
        state, _, collapsed = eng.reconfig_step(
            state, jnp.zeros((e,), bool), new_view, up)
        assert bool(np.asarray(collapsed).all()), round_i
        # restore full membership for the next cycle
        full = jnp.asarray(np.ones((e, m), bool))
        state, installed, _ = eng.reconfig_step(
            state, jnp.ones((e,), bool), full, up)
        assert bool(np.asarray(installed).all()), round_i
        state, _, collapsed = eng.reconfig_step(
            state, jnp.zeros((e,), bool), full, up)
        assert bool(np.asarray(collapsed).all()), round_i


# ---------------------------------------------------------------------------
# General views-list semantics: arbitrary depth + the pend/commit vsn dance


class ScalarViews:
    """Independent scalar model of the reference's membership dance
    (update_members cons, peer.erl:655-672; maybe_change_views vsn
    guard, :1115-1135; transition collapse + commit_vsn, :751-774) for
    one ensemble with all peers up and a fixed leader."""

    def __init__(self, m, depth):
        self.m, self.depth = m, depth
        self.views = [set(range(m))]
        self.view_vsn = 0
        self.pend_vsn = 0
        self.commit_vsn = 0

    def propose(self, new_view, vsn):
        if (vsn <= self.pend_vsn or not new_view
                or len(self.views) >= self.depth):
            return False
        self.views.insert(0, set(new_view))
        self.view_vsn += 1
        self.pend_vsn = vsn
        return True

    def transition(self):
        if len(self.views) <= 1:
            return False
        self.views = [self.views[0]]
        self.view_vsn += 1
        self.commit_vsn = self.pend_vsn
        return True


@pytest.mark.parametrize("seed", range(4))
def test_views_dance_matches_scalar_model(seed):
    """Randomized churn: proposals with stale/fresh vsns and
    transitions, device (V=4) vs the scalar views-list model."""
    rng = np.random.default_rng(seed)
    e, m, depth = 16, 5, 4
    state = eng.init_state(e, m, 8, n_views=depth)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())
    models = [ScalarViews(m, depth) for _ in range(e)]

    for step in range(20):
        if rng.random() < 0.6:
            # propose: random view, vsn sometimes stale
            nv = np.zeros((e, m), bool)
            vsn = np.zeros((e,), np.int32)
            views = []
            for i in range(e):
                size = rng.integers(0, m + 1)
                view = set(rng.choice(m, size=size, replace=False).tolist())
                views.append(view)
                nv[i, list(view)] = True
                vsn[i] = models[i].pend_vsn + rng.integers(0, 2)  # 0=stale
            state, installed = eng.reconfig_propose(
                state, jnp.ones((e,), bool), jnp.asarray(nv),
                jnp.asarray(vsn), up)
            inst = np.asarray(installed)
            for i in range(e):
                assert inst[i] == models[i].propose(views[i], int(vsn[i])), \
                    (seed, step, i)
        else:
            state, collapsed = eng.reconfig_transition(
                state, jnp.ones((e,), bool), up)
            coll = np.asarray(collapsed)
            for i in range(e):
                assert coll[i] == models[i].transition(), (seed, step, i)

        vm = np.asarray(state.view_mask)
        vv = np.asarray(state.view_vsn)
        pv = np.asarray(state.pend_vsn)
        cv = np.asarray(state.commit_vsn)
        for i in range(e):
            mdl = models[i]
            assert vv[i] == mdl.view_vsn, (seed, step, i)
            assert pv[i] == mdl.pend_vsn, (seed, step, i)
            assert cv[i] == mdl.commit_vsn, (seed, step, i)
            got = [set(np.nonzero(vm[i, v])[0].tolist())
                   for v in range(depth)]
            want = [set(v) for v in mdl.views] + \
                [set()] * (depth - len(mdl.views))
            assert got == want, (seed, step, i)


def test_deep_views_quorum_spans_every_view():
    """Three stacked views: a commit needs a majority in ALL of them
    (the msg.erl:377-418 recursion over an arbitrary list)."""
    e, m = 4, 7
    state = eng.init_state(e, m, 8, n_views=4)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    # views: head {0,1,2}, mid {2,3,4}, tail {0..6}
    for view in ([2, 3, 4], [0, 1, 2]):
        nv = np.zeros((e, m), bool)
        nv[:, view] = True
        state, installed = eng.reconfig_propose(
            state, jnp.ones((e,), bool), jnp.asarray(nv),
            jnp.asarray(np.asarray(state.pend_vsn) + 1), up)
        assert bool(np.asarray(installed).all())
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((e,), jnp.int32)
    val = jnp.full((e,), 5, jnp.int32)
    lease = jnp.ones((e,), bool)
    # Up {0,1,2,5,6}: head 3/3, mid 1/3 -> fail.
    up_p = jnp.asarray(np.tile([1, 1, 1, 0, 0, 1, 1], (e, 1)).astype(bool))
    _, res = eng.kv_step(state, kind, slot, val, lease, up_p)
    assert not bool(np.asarray(res.committed).any())
    # Up {0,1,2,3,4}: head 3/3, mid 3/3, tail 5/7 -> commit.
    up_q = jnp.asarray(np.tile([1, 1, 1, 1, 1, 0, 0], (e, 1)).astype(bool))
    _, res = eng.kv_step(state, kind, slot, val, lease, up_q)
    assert bool(np.asarray(res.committed).all())
    # Transition collapses all the way to the head view.
    state, collapsed = eng.reconfig_transition(
        state, jnp.ones((e,), bool), up)
    assert bool(np.asarray(collapsed).all())
    vm = np.asarray(state.view_mask)
    assert vm[:, 0, :3].all() and not vm[:, 1:, :].any()


def test_full_views_list_backpressures():
    """A views list at capacity nacks further proposals until a
    transition frees a slot (the host retries, as after any failed
    try_commit)."""
    e, m = 2, 5
    state = eng.init_state(e, m, 8, n_views=2)
    up = jnp.ones((e, m), bool)
    state, _ = eng.elect_step(state, jnp.ones((e,), bool),
                              jnp.zeros((e,), jnp.int32), up)
    nv = jnp.asarray(np.tile([1, 1, 1, 0, 0], (e, 1)).astype(bool))
    state, installed = eng.reconfig_propose(
        state, jnp.ones((e,), bool), nv,
        jnp.asarray(np.asarray(state.pend_vsn) + 1), up)
    assert bool(np.asarray(installed).all())
    state2, installed = eng.reconfig_propose(
        state, jnp.ones((e,), bool), nv,
        jnp.asarray(np.asarray(state.pend_vsn) + 1), up)
    assert not bool(np.asarray(installed).any())  # full: nack
    state, collapsed = eng.reconfig_transition(
        state, jnp.ones((e,), bool), up)
    assert bool(np.asarray(collapsed).all())
    state, installed = eng.reconfig_propose(
        state, jnp.ones((e,), bool), nv,
        jnp.asarray(np.asarray(state.pend_vsn) + 1), up)
    assert bool(np.asarray(installed).all())  # slot freed


def test_sharded_general_reconfig_matches_single():
    from riak_ensemble_tpu.parallel.mesh import ShardedEngine, make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    e, m = 8, 8
    se = ShardedEngine(make_mesh(4, 2))
    views = [list(range(5))]
    up = jnp.ones((e, m), bool)
    nv = jnp.asarray(np.tile([1, 1, 1, 0, 0, 0, 0, 0], (e, 1)).astype(bool))

    def run(eng_or_se, state):
        state, won = eng_or_se.elect_step(
            state, jnp.ones((e,), bool), jnp.zeros((e,), jnp.int32), up)
        vsn = jnp.ones((e,), jnp.int32)
        state, inst = eng_or_se.reconfig_propose(
            state, jnp.ones((e,), bool), nv, vsn, up)
        state, coll = eng_or_se.reconfig_transition(
            state, jnp.ones((e,), bool), up)
        return won, inst, coll, state

    out_s = run(eng, eng.init_state(e, m, 8, views=views))
    out_m = run(se, se.init_state(e, m, 8, views=views))
    for a, b in zip(jax.tree.leaves(out_s), jax.tree.leaves(out_m)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    won, inst, coll, state = out_s
    assert bool(np.asarray(won).all()) and bool(np.asarray(inst).all())
    assert bool(np.asarray(coll).all())
    np.testing.assert_array_equal(np.asarray(state.commit_vsn), 1)
