"""Headline benchmark: the END-TO-END service, plus the raw kernel.

Scenario 3 of the BASELINE.md ladder: 10k ensembles x 5 peers of mixed
kput/kget.  Two numbers:

1. ``engine_kernel_rounds_per_sec`` — raw ``kv_step_scan`` launches,
   device math only (ballots, quorum reduce, store, Merkle maintenance;
   no host bridge).  An honest kernel number, not a service claim.
2. ``service_linearizable_kv_ops_per_sec`` — the HEADLINE:
   ``BatchedEnsembleService.execute`` end to end (election fold-in,
   host lease check/renewal, device launch, result transfer, corruption
   watch), with client-observed per-batch commit latency recorded —
   p50/p99 reported against the BASELINE.md targets (>= 1M ops/s,
   p99 < 5 ms).

The reference publishes no numbers (BASELINE.md); the driver north-star
target of 1M linearizable ops/sec is the ``vs_baseline`` denominator.

Resilience: the tunneled TPU backend intermittently wedges (observed:
a compile that normally takes 26 s hanging > 10 min, with d2h
transfers additionally degrading dispatch).  A hung bench would leave
the round with NO number, so the orchestrator runs each stage in a
subprocess under a hard timeout and falls back — full shapes → smaller
shapes → forced-CPU — recording the platform and shape actually
measured.  Numbers are never silently substituted: the metric name and
``platform`` field say exactly what ran.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/sec", "vs_baseline": N,
   "p50_commit_latency_ms": ..., "p99_commit_latency_ms": ...,
   "engine_kernel_rounds_per_sec": ..., "platform": ...}

``--smoke`` shrinks shapes for a CPU sanity run (single process).
``--stage ...`` runs one stage in-process (the orchestrator's worker).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def _setup_jax(force_cpu: bool) -> None:
    """Per-stage JAX config: persistent compile cache (retries and
    re-runs skip the 20-40 s compiles) and an optional CPU pin (the
    environment's sitecustomize pins jax_platforms to the TPU tunnel,
    so the pin must override the config, not just the env var)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is best-effort; older jax may lack the knobs
    if force_cpu:
        jax.config.update("jax_platforms", "cpu")


def run_pipelined_service(n_ens: int, n_peers: int, n_slots: int,
                          k: int, seconds: float,
                          depth: int = 2, engine=None) -> dict:
    """Pipelined closed loop — the two-phase async service execution
    (HEADLINE): up to ``depth`` batches in flight via
    ``execute_async``, so batch N's packed d2h transfer + host
    resolve (unpack, mirrors, corruption watch) overlap batch N+1's
    device step instead of serializing after it.  Reports the
    overlapped throughput AND the client-observed per-op commit
    latency (submit → future resolved — each op's real ack time,
    which includes the in-flight dwell the overlap buys throughput
    with)."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                 n_slots, tick=None,
                                 max_ops_per_tick=k,
                                 pipeline_depth=depth, engine=engine)
    if engine is not None:
        # mesh arm: pre-compile the mesh step/pack grid so the loop
        # below measures serving, not first-use compiles (asserted
        # via the serve-phase CompileWatch counter after the run)
        svc.warmup()
    rng = np.random.default_rng(0)
    kind = jnp.asarray(rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)),
                       jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (k, n_ens)), jnp.int32)
    val = jnp.asarray(rng.integers(1, 1 << 20, (k, n_ens)), jnp.int32)
    jax.block_until_ready((kind, slot, val))

    # Warm: compile + first elections, then settle everything.
    for _ in range(depth + 1):
        svc.execute_async(kind, slot, val)
    svc.flush()
    svc.lat_records.clear()

    lat: list = []
    pending: list = []
    ops = 0
    t_end = time.perf_counter() + max(seconds, 1e-3)
    t_start = time.perf_counter()
    while time.perf_counter() < t_end or not lat:
        t0 = time.perf_counter()
        fut = svc.execute_async(kind, slot, val)
        fut.add_waiter(
            lambda _r, t0=t0: lat.append(time.perf_counter() - t0))
        pending.append(fut)
        ops += k * n_ens
    svc.flush()  # idle flush settles the in-flight tail
    elapsed = time.perf_counter() - t_start

    assert all(f.done for f in pending), "pipelined bench: unsettled"
    committed, get_ok, _found, _value = pending[-1].value
    assert (committed | get_ok).all(), "pipelined bench: ops failed"
    lat_ms = np.asarray(lat) * 1000.0
    out = {
        "ops_per_sec": ops / elapsed,
        "p50_ms": float(np.percentile(lat_ms, 50)),
        "p99_ms": float(np.percentile(lat_ms, 99)),
        "batches": len(lat),
        "pipeline_depth": depth,
        "latency_breakdown": {
            c: {"p50": round(v["p50_ms"], 3),
                "p99": round(v["p99_ms"], 3)}
            for c, v in svc.latency_breakdown().items()},
    }
    if engine is not None:
        serve_compiles = int(svc._c_compile.labels("serve").value)
        out["serve_compiles"] = serve_compiles
        assert serve_compiles == 0, (
            "warmed mesh arm paid serve-phase compiles: "
            f"{[e for e in svc._compile_log if e['phase'] == 'serve']}")
    return out


def run_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                seconds: float) -> dict:
    """End-to-end service throughput + client-observed commit latency.

    Two closed loops over the same device-resident workload: the
    PIPELINED loop (depth 2, ``execute_async`` — the headline; see
    :func:`run_pipelined_service`) and the serial loop (each
    iteration blocks on ``execute`` — the depth-1 reference the
    ``serial_*`` keys report, and the A/B that catches a silently
    serialized pipeline).  Per-batch wall time in the serial loop IS
    each op's commit latency: ops enqueue at batch start and resolve
    when the batch returns.
    """
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers, n_slots,
                                 tick=None, max_ops_per_tick=k)
    rng = np.random.default_rng(0)
    # Device-resident op planes (execute's fast path): a TPU-native
    # caller keeps its op queues on device, so the timed loop pays
    # h2d for none of the [K, E] planes — only the packed results
    # come back.  Built + transferred once, outside the timed region.
    kind = jnp.asarray(rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)),
                       jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (k, n_ens)), jnp.int32)
    val = jnp.asarray(rng.integers(1, 1 << 20, (k, n_ens)), jnp.int32)
    jax.block_until_ready((kind, slot, val))

    # Warm up: compile + first elections fold into the launch.
    svc.execute(kind, slot, val)
    svc.execute(kind, slot, val)
    # The warmup records carry the 20-40 s first-compile inside their
    # 'dispatch' component; quoting them as the service's latency
    # breakdown is what made r3's dispatch p99 read 749 ms against a
    # 2.4 ms p50 (VERDICT r3 weak #2 / directive #4).  The breakdown
    # below is STEADY-STATE by construction; mid-run compiles can't
    # occur in this loop (fixed shapes), and flush-path services warm
    # their pow2 depth ladder via repgroup.warmup_kernels.
    svc.lat_records.clear()

    lat = []
    ops = 0
    t_end = time.perf_counter() + max(seconds, 1e-3)
    t_start = time.perf_counter()
    while time.perf_counter() < t_end or not lat:  # >= 1 iteration
        t0 = time.perf_counter()
        committed, get_ok, found, value = svc.execute(kind, slot, val)
        lat.append(time.perf_counter() - t0)
        ops += k * n_ens
    elapsed = time.perf_counter() - t_start

    # Correctness on the final batch: every op acked.
    ok = committed | get_ok
    assert ok.all(), "service bench: ops failed"
    assert (np.asarray(svc.state.leader) >= 0).all()
    lat_ms = np.asarray(lat) * 1000.0
    serial = {
        "serial_ops_per_sec": ops / elapsed,
        "serial_p50_ms": float(np.percentile(lat_ms, 50)),
        "serial_p99_ms": float(np.percentile(lat_ms, 99)),
        # Per-component breakdown (queue_wait/h2d/dispatch/device_d2h/
        # unpack/wal/resolve, p50 AND p99) — where the p99 target's
        # budget actually goes on the serial path.
        "serial_latency_breakdown": {
            c: {"p50": round(v["p50_ms"], 3),
                "p99": round(v["p99_ms"], 3)}
            for c, v in svc.latency_breakdown().items()},
    }
    svc.stop()
    # The HEADLINE: the depth-2 pipelined loop (ops_per_sec/p50/p99 +
    # the enqueue/inflight_wait/resolve breakdown come from it).
    out = run_pipelined_service(n_ens, n_peers, n_slots, k, seconds)
    out.update(serial)
    keyed = run_keyed_service(
        min(n_ens, 1000), n_peers, n_slots, min(k, 16), seconds)
    out["keyed_ops_per_sec"] = keyed["scalar"]
    out["keyed_batched_ops_per_sec"] = keyed["batched"]
    mixed = run_mixed_service(n_ens, n_peers, n_slots, k, seconds)
    out.update(mixed)
    out.update(run_rmw_service(
        min(n_ens, 256), n_peers, n_slots, min(k, 8), seconds))
    out.update(run_skewed_service(
        min(n_ens, 512), n_peers, min(n_slots, 64), min(k, 16),
        seconds))
    # read-heavy rung at the 512-ens shape with the fastpath-off A/B
    # arm (the lease-protected read fast path's headline)
    out.update(run_read_service(
        min(n_ens, 512), n_peers, min(n_slots, 64), min(k, 16),
        seconds))
    # observability-plane A/B (interleaved obs-on/off windows of the
    # headline pipelined loop): the round JSON records the overhead
    # as a measurement, not a claim
    out.update(run_obs_overhead(n_ens, n_peers, n_slots, k, seconds))
    # per-op SLO tracing A/B on the keyed rung (the surface that
    # pays the ring stamps; acceptance bound 2%)
    out.update(run_op_trace_overhead(
        min(n_ens, 512), n_peers, min(n_slots, 64), min(k, 16),
        seconds))
    # native-resolve A/B (interleaved on/off batches of the keyed
    # batched rung with a live WAL — the full resolve half the C
    # kernel replaces; same batch-granular methodology as the obs A/B)
    out.update(run_native_resolve_ab(
        min(n_ens, 512), n_peers, min(n_slots, 64), min(k, 16),
        seconds))
    # native-enqueue A/B (the other half: slab-resident pending ops +
    # per-flush completion slab vs the per-entry pack + per-op future
    # fan-out — same interleaved batch-granular methodology)
    out.update(run_native_enqueue_ab(
        min(n_ens, 512), n_peers, min(n_slots, 64), min(k, 16),
        seconds))
    return out


def run_native_resolve_ab(n_ens: int, n_peers: int, n_slots: int,
                          k: int, seconds: float) -> dict:
    """The native single-pass resolve kernel's A/B
    (``resolve_native_speedup``): the keyed BATCHED workload
    (kput_many/kget_many futures through flush()) with a buffer-sync
    WAL, so one measured batch exercises the whole resolve half the
    kernel replaces — packed-result unpack, mirror-slab scatter, WAL
    record encode — against the pure-Python oracle arm
    (``RETPU_NATIVE_RESOLVE=0``).

    Methodology is PR 6's obs_overhead_pct batch-granular interleave:
    one live service per arm (the knob binds at construction), one
    stream of alternating on/off batches with the pair order flipping
    per iteration, scored by per-arm medians — wall-clock windows on
    a small shared box measure scheduler noise, not the kernel.  The
    native arm's latency breakdown rides along so the JSON shows
    where the batch time actually goes (`resolve`, and the derived
    `resolve_native` kernel share) rather than just a ratio."""
    import shutil
    import tempfile

    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    from riak_ensemble_tpu.parallel import resolve_native

    if resolve_native.get() is None:
        # no toolchain (or knob off in the environment): record the
        # absence instead of a fake 1.0x — and build no services
        return {"resolve_native_speedup": None,
                "resolve_native_available": False}

    keys = [f"key{j}" for j in range(k)]
    vals = [b"v%d" % j for j in range(k // 2)]
    tmp = tempfile.mkdtemp(prefix="bench_native_resolve_")

    def make(env: str) -> BatchedEnsembleService:
        old = os.environ.get("RETPU_NATIVE_RESOLVE")
        os.environ["RETPU_NATIVE_RESOLVE"] = env
        try:
            svc = BatchedEnsembleService(
                WallRuntime(), n_ens, n_peers, n_slots, tick=None,
                max_ops_per_tick=k,
                data_dir=os.path.join(tmp, f"arm{env}"),
                wal_sync="buffer")
        finally:
            if old is None:
                os.environ.pop("RETPU_NATIVE_RESOLVE", None)
            else:
                os.environ["RETPU_NATIVE_RESOLVE"] = old
        batch(svc)  # warm: slots allocate, elections fold in
        svc.lat_records.clear()
        return svc

    def batch(svc: BatchedEnsembleService) -> float:
        t0 = time.perf_counter()
        futs = []
        for e in range(n_ens):
            futs.append(svc.kput_many(e, keys[:k // 2], vals))
            futs.append(svc.kget_many(e, keys[k // 2:]))
        while any(svc.queues):
            svc.flush()
        dt = time.perf_counter() - t0
        assert all(f.done for f in futs), "native A/B: unsettled"
        return dt

    on_svc = off_svc = None
    try:
        on_svc, off_svc = make("1"), make("0")
        assert on_svc._native_resolve is not None, \
            "kernel vanished between availability probe and arm build"
        probe = batch(on_svc)
        n = int(max(seconds, 1.0) * 3.0 / max(probe, 1e-7) / 2)
        n = max(30, min(n, 120))
        on_t: list = []
        off_t: list = []
        for i in range(n):
            order = ((on_svc, on_t), (off_svc, off_t))
            for svc, sink in (order if i % 2 == 0 else order[::-1]):
                sink.append(batch(svc))
        assert on_svc.stats()["native_resolve"]["flushes"] > 0, \
            "native arm never took the kernel"
        breakdown = {
            c: {"p50": round(v["p50_ms"], 3),
                "p99": round(v["p99_ms"], 3)}
            for c, v in on_svc.latency_breakdown().items()}
    finally:
        # stop BEFORE the rmtree: the WAL stores hold open handles
        # into tmp, and an exception mid-loop must not leak services
        for svc in (on_svc, off_svc):
            if svc is not None:
                try:
                    svc.stop()
                except Exception:
                    pass
        shutil.rmtree(tmp, ignore_errors=True)
    on_med = float(np.median(on_t))
    off_med = float(np.median(off_t))
    ops = k * n_ens
    return {
        "resolve_native_available": True,
        "resolve_native_ops_per_sec": ops / on_med,
        "resolve_fallback_ops_per_sec": ops / off_med,
        "resolve_native_speedup": round(off_med / on_med, 3),
        "resolve_ab_samples_per_arm": n,
        "resolve_ab_spread_ms": {
            "on": [round(float(np.percentile(on_t, q)) * 1e3, 1)
                   for q in (10, 90)],
            "off": [round(float(np.percentile(off_t, q)) * 1e3, 1)
                    for q in (10, 90)]},
        # the native arm's per-component breakdown: 'resolve' (future
        # fan-out), 'unpack', 'wal', and the derived 'resolve_native'
        # kernel share — the honest answer to "did the bottleneck
        # move off resolve"
        "resolve_native_latency_breakdown": breakdown,
    }


def run_native_enqueue_ab(n_ens: int, n_peers: int, n_slots: int,
                          k: int, seconds: float) -> dict:
    """The slab enqueue half's A/B (``enqueue_native_speedup``): the
    WAL'd keyed batched rung with ``RETPU_NATIVE_ENQUEUE`` on
    (slab-resident pending ops, one-traversal op-plane pack, per-flush
    completion slab — docs/ARCHITECTURE.md §12) against the per-entry
    pack + per-op future fan-out oracle arm (``=0``).

    Methodology is the PR 6/7 batch-granular interleave verbatim: one
    live service per arm (the knob binds at construction), one stream
    of alternating batches with the pair order flipping per
    iteration, per-arm medians.  The round JSON gets the on arm's
    component breakdown (``queue_wait``/``resolve`` plus the derived
    ``enqueue_native``/``enqueue_fallback`` pack marks), BOTH arms'
    ``queue_wait + resolve`` p50 — the acceptance criterion is that
    combined share cut >= 2x — and the completion-slab ledger, whose
    wakes must equal the op-carrying flush count (one wake per
    flush, observable)."""
    import shutil
    import tempfile

    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    keys = [f"key{j}" for j in range(k)]
    vals = [b"v%d" % j for j in range(k // 2)]
    tmp = tempfile.mkdtemp(prefix="bench_native_enqueue_")

    def make(env: str) -> BatchedEnsembleService:
        svc = _env_scoped(
            "RETPU_NATIVE_ENQUEUE", env,
            lambda: BatchedEnsembleService(
                WallRuntime(), n_ens, n_peers, n_slots, tick=None,
                max_ops_per_tick=k,
                data_dir=os.path.join(tmp, f"arm{env}"),
                wal_sync="buffer"))
        batch(svc)  # warm: slots allocate, elections fold in
        svc.lat_records.clear()
        return svc

    def batch(svc: BatchedEnsembleService) -> float:
        t0 = time.perf_counter()
        futs = []
        for e in range(n_ens):
            futs.append(svc.kput_many(e, keys[:k // 2], vals))
            futs.append(svc.kget_many(e, keys[k // 2:]))
        while any(svc.queues):
            svc.flush()
        dt = time.perf_counter() - t0
        assert all(f.done for f in futs), "enqueue A/B: unsettled"
        return dt

    def qw_res_p50(svc: BatchedEnsembleService) -> float:
        """The acceptance criterion's quantity: the arm's p50
        queue_wait + resolve (enqueue-side wait + settle fan-out)."""
        br = svc.latency_breakdown()
        return round(sum(br.get(c, {}).get("p50_ms", 0.0)
                         for c in ("queue_wait", "resolve")), 3)

    on_svc = off_svc = None
    try:
        on_svc, off_svc = make("1"), make("0")
        assert on_svc._enq_slab and not off_svc._enq_slab
        on_t, off_t, n = _interleaved_ab(on_svc, off_svc, batch,
                                         seconds, 3)
        stats_on = on_svc.stats()
        slab = stats_on["completion_slab"]
        breakdown = {
            c: {"p50": round(v["p50_ms"], 3),
                "p99": round(v["p99_ms"], 3)}
            for c, v in on_svc.latency_breakdown().items()}
        on_qw, off_qw = qw_res_p50(on_svc), qw_res_p50(off_svc)
    finally:
        for svc in (on_svc, off_svc):
            if svc is not None:
                try:
                    svc.stop()
                except Exception:
                    pass
        shutil.rmtree(tmp, ignore_errors=True)
    on_med = float(np.median(on_t))
    off_med = float(np.median(off_t))
    ops = k * n_ens
    return {
        "enqueue_native_available": (
            stats_on["native_enqueue"]["kernel"]),
        "enqueue_native_ops_per_sec": ops / on_med,
        "enqueue_fallback_ops_per_sec": ops / off_med,
        "enqueue_native_speedup": round(off_med / on_med, 3),
        "enqueue_ab_samples_per_arm": n,
        "enqueue_ab_spread_ms": {
            "on": [round(float(np.percentile(on_t, q)) * 1e3, 1)
                   for q in (10, 90)],
            "off": [round(float(np.percentile(off_t, q)) * 1e3, 1)
                    for q in (10, 90)]},
        # the acceptance criterion's two sides: combined queue_wait +
        # fan-out p50 per arm (>= 2x cut is the claim under test)
        "enqueue_queue_wait_resolve_p50_ms": {
            "on": on_qw, "off": off_qw,
            "cut_x": (round(off_qw / on_qw, 2) if on_qw else None)},
        "enqueue_native_latency_breakdown": breakdown,
        # one wake per op-carrying flush, rounds conserved — the
        # completion slab's own ledger rides the round JSON
        "enqueue_completion_slab": {
            **slab,
            "pack_flushes": (
                stats_on["native_enqueue"]["flushes"]
                + stats_on["native_enqueue"]["fallback_flushes"]),
        },
    }


def run_escale_point(n_ens: int, n_peers: int, n_slots: int, k: int,
                     seconds: float, mesh_devices: int = 0) -> dict:
    """One E-scaling datapoint (ROADMAP carried debt: the 1k/2k-ens
    CPU rungs): the headline pipelined device-resident loop plus the
    keyed batched surface at [K, n_ens], so the curve covers both the
    kernel scaling and the host resolve scaling.

    ``mesh_devices`` > 0 serves from a mesh engine sharded over that
    many devices along the 'ens' axis (the shard-wise pack path).
    The mesh arm is WARMED first and CompileWatch-asserts zero
    serve-phase compile events — a mesh number that quietly paid
    mid-serving compiles would not be a serving-path measurement.
    """
    import jax

    engine = None
    if mesh_devices:
        from riak_ensemble_tpu.parallel.mesh import mesh_engine
        engine = mesh_engine(mesh_devices)
    pip = run_pipelined_service(n_ens, n_peers, n_slots, k, seconds,
                                engine=engine)
    n_dev = mesh_devices or 1
    out = {
        "n_ens": n_ens,
        "mesh_devices": mesh_devices,
        "ops_per_sec": round(pip["ops_per_sec"], 1),
        "ops_per_sec_per_device": round(pip["ops_per_sec"] / n_dev, 1),
        "p50_ms": round(pip["p50_ms"], 3),
        "p99_ms": round(pip["p99_ms"], 3),
        "batches": pip["batches"],
    }
    if mesh_devices:
        out["serve_compiles"] = pip["serve_compiles"]
    keyed = run_keyed_batched_only(n_ens, n_peers, n_slots, k,
                                   seconds, engine=engine)
    out["keyed_batched_ops_per_sec"] = round(keyed, 1)
    return out


def run_keyed_batched_only(n_ens: int, n_peers: int, n_slots: int,
                           k: int, seconds: float,
                           engine=None) -> float:
    """The vectorized keyed surface alone (kput_many/kget_many) — the
    E-scaling stage's host-path point without the slow scalar loop."""
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                 n_slots, tick=None,
                                 max_ops_per_tick=k, engine=engine)
    keys = [f"key{j}" for j in range(k)]
    vals = [b"v%d" % j for j in range(k // 2)]
    ops = 0
    warm = True
    t0 = time.perf_counter()
    t_end = t0 + 2 * max(seconds, 1e-3)  # warm round rides inside
    while time.perf_counter() < t_end or not ops:
        futs = []
        for e in range(n_ens):
            futs.append(svc.kput_many(e, keys[:k // 2], vals))
            futs.append(svc.kget_many(e, keys[k // 2:]))
        while any(svc.queues):
            svc.flush()
        assert all(f.done for f in futs), "escale keyed: unsettled"
        if warm:  # first round compiled + elected: restart the clock
            warm = False
            t0 = time.perf_counter()
            t_end = t0 + max(seconds, 1e-3)
            continue
        ops += n_ens * k
    svc.stop()
    return ops / (time.perf_counter() - t0)


def _env_scoped(knob: str, value: str, ctor):
    """Construct a service with ``knob=value`` in the environment
    (the RETPU_* knobs bind at service construction), restoring the
    prior value either way."""
    old = os.environ.get(knob)
    os.environ[knob] = value
    try:
        return ctor()
    finally:
        if old is None:
            os.environ.pop(knob, None)
        else:
            os.environ[knob] = old


def _interleaved_ab(on_svc, off_svc, batch, seconds: float,
                    rounds: int):
    """THE A/B methodology both overhead runners share (fixed work
    at BATCH granularity — see run_obs_overhead's docstring for why
    window estimators lie on a small box): one long stream of
    settled batches alternating on/off with the pair order flipping
    every iteration.  Returns (on_times, off_times, n_per_arm);
    scoring is the caller's (per-arm median + p10/p90 spread via
    :func:`_ab_scores`)."""
    probe = batch(on_svc)
    # sample count per arm from the time budget, clamped so the
    # median is meaningful at the fast shapes (floor: the resolution
    # collapses under ~40 samples on a noisy box) and the slow shapes
    # don't blow the stage budget
    n = int(max(seconds, 1.0) * max(rounds, 1) * 2.0
            / max(probe, 1e-7) / 2)
    n = max(40, min(n, 160))
    on_t: list = []
    off_t: list = []
    for i in range(n):
        # pair order flips every iteration so a monotone box drift
        # cannot masquerade as an arm effect
        order = ((on_svc, on_t), (off_svc, off_t))
        for svc, sink in (order if i % 2 == 0 else order[::-1]):
            sink.append(batch(svc))
    return on_t, off_t, n


def _ab_scores(prefix: str, on_t, off_t, n: int, ops: int) -> dict:
    """Per-arm medians + overhead + p10/p90 spread, under
    ``{prefix}_on_...``/``{prefix}_off_...`` keys."""
    on_med = float(np.median(on_t))
    off_med = float(np.median(off_t))
    return {
        f"{prefix}_on_ops_per_sec": ops / on_med,
        f"{prefix}_off_ops_per_sec": ops / off_med,
        f"{prefix}_on_batch_ms": round(on_med * 1e3, 3),
        f"{prefix}_off_batch_ms": round(off_med * 1e3, 3),
        f"{prefix}_overhead_pct": round(
            (on_med - off_med) / off_med * 100.0, 2),
        f"{prefix}_ab_samples_per_arm": n,
        # p90/p10 spread per arm: how much the box wobbled while
        # measuring — read the overhead number against this
        f"{prefix}_ab_spread_ms": {
            "on": [round(float(np.percentile(on_t, q)) * 1e3, 1)
                   for q in (10, 90)],
            "off": [round(float(np.percentile(off_t, q)) * 1e3, 1)
                    for q in (10, 90)]},
    }


def run_obs_overhead(n_ens: int, n_peers: int, n_slots: int, k: int,
                     seconds: float, rounds: int = 3) -> dict:
    """The observability-plane A/B arm (acceptance bound: the obs-on
    headline pipelined loop within 3% of ``RETPU_OBS=0`` on the same
    box).

    Methodology: FIXED WORK at BATCH granularity.  One live service
    per arm (the knob is read at construction), then one long stream
    of settled batches alternating on/off/on/off with the pair order
    flipping every iteration, scored by each arm's per-batch MEDIAN.
    Wall-clock windows cannot do this job on a small shared box: a
    window at the 512-ens CPU shape holds ~8 batches and back-to-back
    identical runs swing ±50%, while scheduler spikes hit single
    windows, so window-level best-of/paired-delta estimators measured
    phantom overheads of 13-50% where the batch-granular median
    reproduces at ~1%.  Interleaving at the batch level gives both
    arms the same drift and ~100 samples each; the median kills the
    spikes.  Negative overhead is box noise in the bound's favor."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    rng = np.random.default_rng(0)
    kind = jnp.asarray(rng.choice([eng.OP_PUT, eng.OP_GET],
                                  (k, n_ens)), jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (k, n_ens)), jnp.int32)
    val = jnp.asarray(rng.integers(1, 1 << 20, (k, n_ens)), jnp.int32)
    jax.block_until_ready((kind, slot, val))

    def make(env: str) -> BatchedEnsembleService:
        """One live service per arm (the knob is read at service
        construction); warmed outside every timed window."""
        svc = _env_scoped(
            "RETPU_OBS", env,
            lambda: BatchedEnsembleService(WallRuntime(), n_ens,
                                           n_peers, n_slots,
                                           tick=None,
                                           max_ops_per_tick=k,
                                           pipeline_depth=2))
        for _ in range(3):
            svc.execute_async(kind, slot, val)
        svc.flush()
        return svc

    def batch(svc: BatchedEnsembleService) -> float:
        t0 = time.perf_counter()
        svc.execute_async(kind, slot, val)
        svc.flush()  # settle: the measured unit is one full batch
        return time.perf_counter() - t0

    on_svc, off_svc = make("1"), make("0")
    on_t, off_t, n = _interleaved_ab(on_svc, off_svc, batch,
                                     seconds, rounds)
    on_svc.stop()
    off_svc.stop()
    return _ab_scores("obs", on_t, off_t, n, k * n_ens)


def run_op_trace_overhead(n_ens: int, n_peers: int, n_slots: int,
                          k: int, seconds: float,
                          rounds: int = 3) -> dict:
    """Per-op SLO tracing A/B on the KEYED rung (acceptance bound:
    the ring within 2% of ``RETPU_SLO_RING=0``).

    The per-op ring fold lives on the kput_many/kget_many settle
    path, which the device-resident pipelined loop of
    ``run_obs_overhead`` never exercises — so the tracing overhead
    needs its own arm on the surface that actually pays it.  Both
    arms keep the FULL obs plane on (flush spans, tenant counters,
    flight ring — whose keyed-rung cost predates this round); the
    off arm disables the per-op ring ALONE via ``RETPU_SLO_RING=0``,
    so the delta isolates the tracing this A/B is accountable for.
    Same methodology as run_obs_overhead: one live service per arm,
    one long interleaved stream of settled keyed batches with the
    pair order flipping, per-arm MEDIAN per-batch time (window
    estimators lie on a small box)."""
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    keys = [f"key{j}" for j in range(k)]
    vals = [b"v%d" % j for j in range(k // 2)]

    def make(env: str) -> BatchedEnsembleService:
        svc = _env_scoped(
            "RETPU_SLO_RING", env,
            lambda: BatchedEnsembleService(WallRuntime(), n_ens,
                                           n_peers, n_slots,
                                           tick=None,
                                           max_ops_per_tick=k))
        for _ in range(2):  # compile + first elections, outside timing
            batch(svc)
        return svc

    def batch(svc: BatchedEnsembleService) -> float:
        t0 = time.perf_counter()
        futs = []
        for e in range(n_ens):
            futs.append(svc.kput_many(e, keys[:k // 2], vals))
            futs.append(svc.kget_many(e, keys[k // 2:]))
        while any(svc.queues):
            svc.flush()
        assert all(f.done for f in futs), "op-trace A/B: unsettled"
        return time.perf_counter() - t0

    on_svc, off_svc = make("4096"), make("0")
    on_t, off_t, n = _interleaved_ab(on_svc, off_svc, batch,
                                     seconds, rounds)
    # sanity: the traced arm really recorded per-op samples
    snap = on_svc.obs_registry.snapshot()
    op_lat = snap.get("retpu_op_latency_ms", {})
    traced = int(op_lat.get("count", 0)) + sum(
        int(ch.get("count", 0))
        for ch in op_lat.get("by_label", {}).values())
    on_svc.stop()
    off_svc.stop()
    out = _ab_scores("op_trace", on_t, off_t, n, k * n_ens)
    out["op_trace_samples_recorded"] = traced
    return out


def _non_marks():
    """Flight-record fields that are shape/identity metadata, not
    latency marks — the recorder's own list, so tail attribution and
    the dump's dominant-mark argmax can never drift apart."""
    from riak_ensemble_tpu.obs.flightrec import META_FIELDS
    return META_FIELDS


def run_mixed_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                      seconds: float) -> dict:
    """The REALISTIC-mix rung (VERDICT r3 #5): every iteration builds
    FRESH host-side op planes — random slots, a
    PUT/GET/CAS/RMW/tombstone mix per batch — with plane construction
    INSIDE the timed loop, and
    feeds them through the host-array ``execute`` path (per-batch h2d
    included).  This is what a host-fed client actually pays per
    batch; the device-resident headline above is the TPU-native
    caller's number.  CAS rows carry real expected versions (half
    fresh-create (0,0), half against the previous batch's committed
    versions), RMW rows run table funs (add/max/xor — the fused
    kmodify's op kind, so mixed_p99 tracks the device RMW cost),
    tombstone writes are puts of 0, and tombstone READS are
    gets of slots a delete just cleared."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers, n_slots,
                                 tick=None, max_ops_per_tick=k)
    rng = np.random.default_rng(1)

    def build(prev_vsn):
        kind = rng.choice(
            [eng.OP_PUT, eng.OP_GET, eng.OP_CAS, eng.OP_RMW,
             eng.OP_PUT],
            (k, n_ens), p=[0.35, 0.3, 0.15, 0.1, 0.1]).astype(np.int32)
        slot = rng.integers(0, n_slots, (k, n_ens)).astype(np.int32)
        val = rng.integers(1, 1 << 20, (k, n_ens)).astype(np.int32)
        # last PUT band is tombstone writes (val 0 = delete)...
        tomb = (kind == eng.OP_PUT) & (rng.random((k, n_ens)) < 0.2)
        val[tomb] = 0
        exp_e = np.zeros((k, n_ens), np.int32)
        exp_s = np.zeros((k, n_ens), np.int32)
        # RMW rows: fun code rides the exp_epoch plane, operand the
        # val plane (the single-round device kmodify)
        rmw = kind == eng.OP_RMW
        exp_e[rmw] = rng.choice(
            [eng.RMW_ADD, eng.RMW_MAX, eng.RMW_BXOR],
            int(rmw.sum())).astype(np.int32)
        if prev_vsn is not None:
            # half the CAS rows guard against versions committed by
            # the PREVIOUS batch (real conflict behavior: some match,
            # some lost a race to this batch's earlier rounds)
            cas = kind == eng.OP_CAS
            use_prev = cas & (rng.random((k, n_ens)) < 0.5)
            pe, ps = prev_vsn
            exp_e[use_prev] = pe[use_prev]
            exp_s[use_prev] = ps[use_prev]
        return kind, slot, val, exp_e, exp_s

    # warm (compile both the exp and no-exp shapes)
    kind, slot, val, exp_e, exp_s = build(None)
    svc.execute(kind, slot, val, exp_epoch=exp_e, exp_seq=exp_s)
    svc.lat_records.clear()  # tail attribution wants steady state

    lat = []
    recs = []  # per-batch launch-latency record, aligned with lat
    ops = commits = gets_ok = 0
    prev_vsn = None
    t_end = time.perf_counter() + max(seconds, 1e-3)
    t_start = time.perf_counter()
    while time.perf_counter() < t_end or not lat:
        t0 = time.perf_counter()
        kind, slot, val, exp_e, exp_s = build(prev_vsn)
        committed, get_ok, found, value = svc.execute(
            kind, slot, val, exp_epoch=exp_e, exp_seq=exp_s)
        lat.append(time.perf_counter() - t0)
        # tail attribution rides the obs flight recorder (per-flush
        # record incl. flush_id — the same ring an anomaly dump
        # snapshots); lat_records is the RETPU_OBS=0 fallback
        recs.append(dict(svc.flight.records[-1])
                    if svc.flight.records
                    else (dict(svc.lat_records[-1])
                          if svc.lat_records else {}))
        ops += k * n_ens
        commits += int(committed.sum())
        gets_ok += int(get_ok.sum())
        # feed committed versions to the next batch's CAS rows: one
        # extra launch-free approximation — versions advance per
        # commit, so "previous batch's version" means exp planes built
        # from the device state would need a d2h; instead CAS guards
        # mix (0,0) creates with stale guesses, exercising BOTH CAS
        # outcomes (the point is mixed-kernel cost, not CAS hit rate)
        prev_vsn = (exp_e, exp_s)
    elapsed = time.perf_counter() - t_start

    # sanity: the mix must exercise all three kernel families
    assert commits > 0 and gets_ok > 0, "mixed bench: degenerate mix"
    lat_ms = np.asarray(lat) * 1000.0
    p50 = float(np.percentile(lat_ms, 50))
    # TAIL ATTRIBUTION: for every batch slower than 5x the rung's own
    # p50, name the latency mark that dominated its launch record —
    # so the mixed p99 points at a cause (d2h stall, exchange sweep,
    # plane build outside the record → 'untracked') instead of being
    # an unexplained number in the round JSON.
    tail_causes: dict = {}
    n_tail = 0
    for ms, rec in zip(lat_ms.tolist(), recs):
        if ms <= 5 * p50:
            continue
        n_tail += 1
        comps = {c: v for c, v in rec.items()
                 if c not in _non_marks()}
        tracked = sum(comps.values()) * 1e3
        if not comps or tracked < ms / 2:
            # the launch record explains under half the batch time:
            # the stall was outside the launch (host plane build, GC,
            # scheduler) — say so rather than blaming a component
            cause = "untracked_host"
        else:
            cause = max(comps, key=comps.get)
        tail_causes[cause] = tail_causes.get(cause, 0) + 1
    return {
        "mixed_ops_per_sec": ops / elapsed,
        "mixed_p50_ms": p50,
        "mixed_p99_ms": float(np.percentile(lat_ms, 99)),
        "mixed_commit_fraction": round(commits / max(ops, 1), 3),
        "mixed_tail_batches": n_tail,
        "mixed_tail_causes": tail_causes,
        "mixed_tail_top_cause": (max(tail_causes, key=tail_causes.get)
                                 if tail_causes else None),
        # flight-recorder evidence: trigger firings during the rung
        # (an anomaly here comes with a ring+fingerprint dump when
        # RETPU_OBS_DUMP_DIR is set — the diagnosable mixed-p99)
        "mixed_flight_anomalies": svc.flight.anomalies,
    }


def run_rmw_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                    seconds: float) -> dict:
    """The RMW rung: a counter-increment STORM — k concurrent
    kmodify(rmw:add 1) of ONE key per ensemble per iteration — as a
    device vs host-fallback A/B.

    The device arm resolves the funref against the mod-fun table: all
    k increments fuse into k one-round OP_RMW ops in a single flush
    and can never CAS-conflict.  The host arm runs the same int32
    semantics as a plain callable (table-unresolvable), taking the
    classic read → fn → CAS cycle: every attempt in a contended flush
    shares one read version, one CAS wins, the rest conflict and
    retry under jittered backoff — rounds per op grow with contention
    instead of staying at 1.  Reports ops/s, flushes per converged
    iteration for both arms, and the speedup."""
    from riak_ensemble_tpu import funref
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    out: dict = {}
    for arm in ("device", "host"):
        svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                     n_slots, tick=None,
                                     max_ops_per_tick=k)
        if arm == "device":
            fn = funref.ref("rmw:add", 1)
        else:
            def fn(vsn, cur):  # same int32 semantics, host-only
                return funref.i32(int(cur) + 1)

        def one_round():
            futs = [svc.kmodify(e, "ctr", fn, 0,
                                retries=2 * k + 4)
                    for e in range(n_ens) for _ in range(k)]
            flushes0 = svc._flush_calls
            while not all(f.done for f in futs):
                svc.flush()
            assert all(f.value[0] == "ok" for f in futs), \
                f"rmw bench ({arm}): increments failed"
            return len(futs), svc._flush_calls - flushes0

        one_round()  # warm: compile, elections, slot allocation
        ops = flushes = iters = 0
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or not iters:
            n, fl = one_round()
            ops += n
            flushes += fl
            iters += 1
        elapsed = time.perf_counter() - t0
        if arm == "device":
            assert svc.rmw_device_fastpath > 0, \
                "device arm never took the RMW fast path"
            assert svc.rmw_conflicts == 0, \
                "device RMWs must not CAS-conflict"
        out[f"rmw_{arm}_ops_per_sec"] = ops / elapsed
        out[f"rmw_{arm}_flushes_per_round"] = flushes / iters
        out[f"rmw_{arm}_conflicts"] = svc.rmw_conflicts
        svc.stop()
    out["rmw_device_speedup"] = (out["rmw_device_ops_per_sec"]
                                 / out["rmw_host_ops_per_sec"])
    return out


def run_skewed_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                       seconds: float, warm: bool = True,
                       baseline: bool = True) -> dict:
    """The SKEWED-load rung — active-column compaction's target
    shape: zipf-distributed ensemble pick, so a handful of hot
    ensembles carry deep queues while most of the [K, E] grid idles
    (the partial-load shape a production front-end actually sees; one
    hot ensemble still forces the full K bucket across all E
    columns).  Keyed kput/kget futures through flush().

    ``warm`` pre-compiles the (K, A) bucket grid first (the dispatch
    p99 fix — without it, first-use compiles of each new bucket land
    inside the timed loop).  ``baseline`` also runs the identical
    loop with compaction disabled (RETPU_COMPACT=0 semantics), so the
    JSON carries the compaction speedup as an A/B, not a claim.
    Reports payload_bytes_per_flush and grid_occupancy so the
    trajectory tracks a regression that re-inflates the transfer."""
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    def arm(compact: bool) -> dict:
        svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                     n_slots, tick=None,
                                     max_ops_per_tick=k)
        svc._compact = compact
        if warm:
            svc.warmup()
        rng = np.random.default_rng(3)
        n_draw = 4 * k

        def one_round():
            ens = np.minimum(rng.zipf(1.5, n_draw) - 1, n_ens - 1)
            futs = []
            for i, e in enumerate(ens.tolist()):
                if i % 2:
                    futs.append(svc.kget(e, f"key{i % 4}"))
                else:
                    futs.append(svc.kput(e, f"key{i % 4}", i + 1))
            while any(svc.queues):
                svc.flush()
            assert all(f.done for f in futs), "skewed bench: unsettled"
            return len(futs)

        one_round()  # slots allocate; elections fold in
        svc.payload_bytes = 0
        svc.payload_bytes_full_width = 0
        svc._occ_sum = 0.0
        svc._occ_launches = 0
        f0 = svc.flushes
        ops = 0
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or not ops:
            ops += one_round()
        elapsed = time.perf_counter() - t0
        flushes = max(svc.flushes - f0, 1)
        st = svc.stats()
        svc.stop()
        return {
            "ops_per_sec": ops / elapsed,
            "payload_bytes_per_flush": svc.payload_bytes / flushes,
            "payload_bytes_full_width_per_flush":
                svc.payload_bytes_full_width / flushes,
            "grid_occupancy": round(st["grid_occupancy"], 4),
        }

    a = arm(True)
    out = {
        "skewed_ops_per_sec": a["ops_per_sec"],
        "payload_bytes_per_flush": round(
            a["payload_bytes_per_flush"], 1),
        "payload_bytes_full_width_per_flush": round(
            a["payload_bytes_full_width_per_flush"], 1),
        "grid_occupancy": a["grid_occupancy"],
    }
    if baseline:
        b = arm(False)
        out["skewed_baseline_ops_per_sec"] = b["ops_per_sec"]
        out["skewed_compaction_speedup"] = round(
            a["ops_per_sec"] / b["ops_per_sec"], 2)
    return out


def run_read_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                     seconds: float, warm: bool = True,
                     baseline: bool = True) -> dict:
    """The READ-HEAVY rung (90/10 kget/kput over pre-populated keys)
    — the lease-protected read fast path's target shape, as a
    fastpath-on vs fastpath-off A/B.

    With the fast path on, the 90% reads are answered from the
    leader's committed host mirror (no OP_GET row, no flush) and only
    the writes launch; the off arm routes every read through the
    device round — write rounds and read rounds compete for the same
    [K, E] grid.  Reports both arms' ops/sec, the speedup, the
    fast-path hit rate + miss reasons, per-round latency, and an
    EQUIVALENCE sweep: after the timed loop every key is read through
    the fast path AND through a forced device round, and the values
    must agree (the linearizable-read contract, cheap form)."""
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    # disjoint read/write key sets: the rung's reads are UNCONTENDED
    # (the hit-rate tripwire's premise) — writes land on their own
    # keys, so no read parks on a pending same-slot write
    n_keys = max(1, min(n_slots // 2, 8))
    keys = [f"key{j}" for j in range(n_keys)]
    wkeys = [f"wkey{j}" for j in range(n_keys)]

    def arm(fast: bool) -> dict:
        svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                     n_slots, tick=None,
                                     max_ops_per_tick=k)
        svc.set_fast_reads(fast)
        if warm:
            svc.warmup()
        # populate every key so the off arm's reads genuinely launch
        # (absent keys short-circuit NOTFOUND in both arms)
        futs = [svc.kput(e, kk, b"r%d" % j)
                for e in range(n_ens)
                for j, kk in enumerate(keys + wkeys)]
        while any(svc.queues):
            svc.flush()
        assert all(f.done and f.value[0] == "ok" for f in futs), \
            "read bench: populate failed"

        # EXACTLY ceil(k/10) writes per ensemble per round — the
        # 90/10 mix with a STABLE flush K bucket, so the warm round
        # compiles every shape the timed loop uses (varying write
        # draws would bounce the pow2 bucket and bill fresh XLA
        # compiles to random rounds).  Both arms ride the VECTORIZED
        # surface (kget_many/kput_many): the scalar path's per-op
        # Python would cap the fast arm long before the device does,
        # understating exactly the device-round cost this rung
        # measures.
        n_writes = max(1, (k + 9) // 10)
        read_keys = [keys[j % n_keys] for j in range(k - n_writes)]
        wvals = [b"w%d" % j for j in range(n_writes)]

        # failed results accumulate across EVERY round (not just the
        # final one) so a mid-run blip can't hide inside the
        # throughput number
        failed = [0]

        def one_round(shift: int = 0):
            futs = []
            wk = [wkeys[(shift + j) % n_keys] for j in range(n_writes)]
            for e in range(n_ens):
                futs.append(svc.kget_many(e, read_keys))
                futs.append(svc.kput_many(e, wk, wvals))
            while any(svc.queues):
                svc.flush()
            svc.flush()  # settle any in-flight tail
            assert all(f.done for f in futs), "read bench: unsettled"
            failed[0] += sum(1 for f in futs for r in f.value
                             if r[0] != "ok")
            return futs, n_ens * len(read_keys)

        # TWO warm rounds: the first's reads may still miss (the
        # populate flush's compile outlived its own lease grant), so
        # it re-leases and serves full-grid; the second exercises the
        # real steady state — fast reads + the write-only small-K
        # flush — compiling that shape outside the measured window
        # and outside the hit-rate tripwire
        one_round()
        one_round()
        svc.read_fastpath_hits = 0
        svc.read_fastpath_misses = 0
        svc.read_fastpath_miss_reasons.clear()
        failed[0] = 0  # warm rounds excluded, like the counters

        # -- phase 1: the 90/10 MIXED loop (write-coupled number:
        # every round still pays its write flush, now K=ceil(k/10)
        # instead of K=k — the reclaimed-grid write win rides here)
        lat: list = []
        ops = reads = rounds = 0
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or not lat:
            tb = time.perf_counter()
            futs, n_reads = one_round(shift=rounds)
            lat.append(time.perf_counter() - tb)
            ops += n_ens * k
            reads += n_reads
            rounds += 1
        elapsed = time.perf_counter() - t0
        assert failed[0] == 0, \
            f"read bench: {failed[0]} op(s) failed across the mix"

        # -- phase 2: the UNCONTENDED read-only loop — the
        # decoupling headline.  Fast-path rounds never launch (reads
        # answer from the mirror; the periodic lease-renewal round
        # when the margin trips is part of the honest steady state);
        # the off arm pays a full device round per batch.
        ro_reads = 0
        ro_lat: list = []
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or not ro_lat:
            tb = time.perf_counter()
            futs = [svc.kget_many(e, read_keys)
                    for e in range(n_ens)]
            while any(svc.queues):
                svc.flush()
            svc.flush()
            assert all(f.done for f in futs), "read bench: unsettled"
            failed[0] += sum(1 for f in futs for r in f.value
                             if r[0] != "ok")
            ro_lat.append(time.perf_counter() - tb)
            ro_reads += n_ens * len(read_keys)
        ro_elapsed = time.perf_counter() - t0
        assert failed[0] == 0, \
            f"read bench: {failed[0]} read(s) failed (read-only phase)"
        # counters snapshot BEFORE the equivalence sweep (its forced
        # device reads must not pollute the hit-rate number)
        hits = svc.read_fastpath_hits
        misses = svc.read_fastpath_misses
        miss_reasons = dict(svc.read_fastpath_miss_reasons)

        # equivalence sweep: fast-path answers == forced device-round
        # answers for every key (run on the FAST arm; trivially true
        # on the off arm)
        equiv = 0
        if fast:
            for e in range(0, n_ens, max(1, n_ens // 16)):
                fast_futs = [svc.kget(e, kk) for kk in keys]
                svc.set_fast_reads(False)
                dev_futs = [svc.kget(e, kk) for kk in keys]
                while any(svc.queues):
                    svc.flush()
                svc.set_fast_reads(True)
                for kk, ff, df in zip(keys, fast_futs, dev_futs):
                    assert ff.value == df.value, (
                        "fast/device read divergence at "
                        f"({e}, {kk}): {ff.value!r} vs {df.value!r}")
                    equiv += 1
        flushes = svc.stats()["flushes"]
        svc.stop()
        lat_ms = np.asarray(lat) * 1e3
        return {
            "ops_per_sec": ops / elapsed,
            "read_ops_per_sec": reads / elapsed,
            "read_only_ops_per_sec": ro_reads / ro_elapsed,
            "read_only_p50_ms": float(
                np.percentile(np.asarray(ro_lat) * 1e3, 50)),
            "p50_ms": float(np.percentile(lat_ms, 50)),
            "p99_ms": float(np.percentile(lat_ms, 99)),
            "hits": hits, "misses": misses,
            "hit_rate": hits / max(hits + misses, 1),
            "miss_reasons": miss_reasons,
            "flushes": flushes,
            "equivalence_checked": equiv,
        }

    a = arm(True)
    out = {
        "read_service_ops_per_sec": a["ops_per_sec"],
        "read_only_ops_per_sec": a["read_only_ops_per_sec"],
        "read_only_p50_ms": round(a["read_only_p50_ms"], 3),
        "read_p50_ms": round(a["p50_ms"], 3),
        "read_p99_ms": round(a["p99_ms"], 3),
        "read_fastpath_hits": a["hits"],
        "read_fastpath_misses": a["misses"],
        "read_hit_rate": round(a["hit_rate"], 4),
        "read_miss_reasons": a["miss_reasons"],
        "read_flushes": a["flushes"],
        "read_equivalence_checked": a["equivalence_checked"],
        "read_equivalence_ok": True,  # the sweep asserts on mismatch
    }
    if baseline:
        b = arm(False)
        # the 90/10 loop's A/B: write-coupled (every round keeps its
        # write flush) — the reclaimed-grid mixed-throughput win
        out["read_baseline_ops_per_sec"] = b["ops_per_sec"]
        out["read_mixed_speedup"] = round(
            a["ops_per_sec"] / b["ops_per_sec"], 2)
        # the read-only A/B: the decoupling headline — mirror-served
        # reads vs a device round per batch
        out["read_baseline_only_ops_per_sec"] = \
            b["read_only_ops_per_sec"]
        out["read_baseline_flushes"] = b["flushes"]
        out["read_fastpath_speedup"] = round(
            a["read_only_ops_per_sec"] / b["read_only_ops_per_sec"],
            2)
    return out


def run_keyed_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                      seconds: float) -> float:
    """The FUTURE-BASED keyed path: kput/kget client futures queued
    per ensemble, resolved through flush() against the real host
    payload store (values are Python bytes behind int32 handles).
    This measures what a keyed client observes — per-op Python
    bookkeeping included — as distinct from the bulk array surface.
    """
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers, n_slots,
                                 tick=None, max_ops_per_tick=k)
    # Warm up: allocate slots, compile the flush shape, elect.
    futs = [svc.kput(e, f"key{j}", b"w%d" % j)
            for e in range(n_ens) for j in range(k)]
    while any(svc.queues):
        svc.flush()
    assert all(f.done and f.value[0] == "ok" for f in futs)

    ops = 0
    t_end = time.perf_counter() + max(seconds, 1e-3)
    t0 = time.perf_counter()
    while time.perf_counter() < t_end or not ops:
        futs = []
        for e in range(n_ens):
            for j in range(k // 2):
                futs.append(svc.kput(e, f"key{j}", b"v%d" % j))
            for j in range(k // 2, k):
                futs.append(svc.kget(e, f"key{j}"))
        while any(svc.queues):
            svc.flush()
        ops += len(futs)
    elapsed = time.perf_counter() - t0
    assert all(f.done and f.value[0] == "ok" for f in futs), \
        "keyed bench: ops failed"
    scalar_rate = ops / elapsed

    # The VECTORIZED keyed surface (kput_many/kget_many): same keyed
    # semantics, struct-of-arrays queue entries, one future per batch.
    keys = [f"key{j}" for j in range(k)]
    vals = [b"v%d" % j for j in range(k // 2)]
    ops = 0
    t_end = time.perf_counter() + max(seconds, 1e-3)
    t0 = time.perf_counter()
    while time.perf_counter() < t_end or not ops:
        futs = []
        for e in range(n_ens):
            futs.append(svc.kput_many(e, keys[:k // 2], vals))
            futs.append(svc.kget_many(e, keys[k // 2:]))
        while any(svc.queues):
            svc.flush()
        ops += n_ens * k
        # same parity check as the scalar phase: EVERY batch op acked
        assert all(f.done and all(r[0] == "ok" for r in f.value)
                   for f in futs), "keyed_many bench: ops failed"
    elapsed = time.perf_counter() - t0
    return {"scalar": scalar_rate, "batched": ops / elapsed}


def run_repgroup(seconds: float, smoke: bool,
                 baseline: bool = True) -> dict:
    """Cross-host replication-group rung: a 3-host group, fsync WALs,
    host-majority commit barrier.  Measures the keyed client surface
    end to end — what the availability story costs per op vs the
    single-process service.

    Round 6: the main arm ships changed-slot DELTA frames (one
    coalesced raw frame per flush per link, batched replica apply);
    the ``baseline`` arm re-runs the identical workload with
    ``RETPU_REPL_DELTA=0`` semantics (full-plane frames) and reports
    ``repl_delta_speedup``.  Both arms meter shipped bytes per entry
    against the full-plane equivalent and break the leader's
    replication cost into build/encode/ack components.  The smoke
    shape runs the replica hosts IN PROCESS (threaded servers, shared
    jit cache) and additionally verifies delta/full equivalence: every
    replica lane's engine state must be bit-equal to the leader's."""
    n_ens, n_slots, k = (16, 16, 8) if smoke else (64, 32, 16)
    out = _repgroup_arm(seconds, smoke, n_ens, n_slots, k, delta=True)
    res = {
        "repgroup_ops_per_sec": out["ops_per_sec"],
        "repgroup_p50_ms": out["p50_ms"],
        "repgroup_p99_ms": out["p99_ms"],
        "repl_bytes_per_entry": out["bytes_per_entry"],
        "repl_bytes_per_entry_full_plane": out["bytes_full_equiv"],
        "repl_delta_entries": out["delta_entries"],
        "repl_full_entries": out["full_entries"],
        "repl_ship_breakdown_ms": out["breakdown_ms"],
    }
    if "equivalence_ok" in out:
        res["repl_equivalence_ok"] = out["equivalence_ok"]
    if baseline:
        base = _repgroup_arm(seconds, smoke, n_ens, n_slots, k,
                             delta=False)
        res["repgroup_baseline_ops_per_sec"] = base["ops_per_sec"]
        res["repl_bytes_per_entry_baseline"] = base["bytes_per_entry"]
        res["repl_delta_speedup"] = round(
            out["ops_per_sec"] / max(base["ops_per_sec"], 1e-9), 3)
    return res


def _repgroup_spawn_subprocess(n_ens, n_slots, tmp, i, procs):
    """One replica host OS process (the full-shape arm: real failure
    domains, real sockets, real fsync).  The child lands in ``procs``
    the moment it exists — BEFORE the ready-line parse — so a
    malformed ready line can't leak a live replica past the caller's
    SIGKILL sweep."""
    import subprocess
    import textwrap

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {repo!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        # replica warmup compiles the same pow2 ladder as the
        # leader: share the persistent compile cache or each
        # child pays minutes of XLA compile on a 1-core box
        jax.config.update("jax_compilation_cache_dir",
                          {repo!r} + "/.jax_cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
        from riak_ensemble_tpu.parallel import repgroup
        repgroup.main(["--n-ens", "{n_ens}", "--group-size", "3",
                       "--n-slots", "{n_slots}", "--fast",
                       "--data-dir", {tmp!r} + "/r{i}"])
    """)
    # stderr → DEVNULL and stdout drained by a daemon thread after
    # the ready line: replicas live for the whole bench, and a chatty
    # child blocking on a full 64 KiB pipe would stop acking and
    # stall the quorum (review r4)
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True,
                         env=env)
    procs.append(p)
    line = p.stdout.readline()
    assert line, "repgroup replica died before ready line"
    parts = dict(kv.split("=") for kv in line.split()[2:])
    import threading
    threading.Thread(target=lambda f=p.stdout: [None for _ in f],
                     daemon=True).start()
    return int(parts["repl"])


def _repgroup_arm(seconds: float, smoke: bool, n_ens: int,
                  n_slots: int, k: int, delta: bool) -> dict:
    import shutil
    import signal
    import tempfile

    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel import repgroup
    from riak_ensemble_tpu.parallel.batched_host import WallRuntime

    tmp = tempfile.mkdtemp(prefix="bench_repgroup_")
    procs = []
    servers = []
    try:
        ports = []
        if smoke:
            for i in (1, 2):
                servers.append(repgroup.ReplicaServer(
                    n_ens, 3, n_slots, data_dir=f"{tmp}/r{i}",
                    config=fast_test_config()))
            ports = [s.repl_port for s in servers]
        else:
            for i in (1, 2):
                ports.append(_repgroup_spawn_subprocess(
                    n_ens, n_slots, tmp, i, procs))
        svc = repgroup.ReplicatedService(
            WallRuntime(), n_ens, 1, n_slots, group_size=3,
            peers=[("127.0.0.1", p) for p in ports],
            ack_timeout=60.0, max_ops_per_tick=k,
            config=fast_test_config(), data_dir=tmp + "/leader",
            # the PR-1 async launch pipeline: overlap round N+1's
            # device step with round N's resolve/build/ship (the
            # repl_window ack pipeline stacks on top — settles stay
            # quorum-barriered either way)
            pipeline_depth=2)
        if not delta:
            svc._repl_delta = False  # the RETPU_REPL_DELTA=0 arm
        repgroup.warmup_kernels(svc)
        assert svc.takeover(), "repgroup bench: takeover failed"

        keys = [f"key{j}" for j in range(k)]
        vals = [b"v%d" % j for j in range(k // 2)]

        # smoke: writes rotate over a QUARTER of the columns per
        # round — the skewed serving shape (§7/§10 premise: the live
        # write set is sparse relative to the grid), so the byte
        # meter exercises the payload-proportional-to-change property
        # the tier-1 tripwire guards.  The full shape keeps the
        # seed's dense round unchanged, for ops_per_sec comparability
        # across bench rounds.
        stride = 4 if smoke else 1
        rnd = [0]

        def one_round():
            # dense warm round regardless of skew: every column
            # allocates its slots and elects BEFORE the meter starts
            futs = []
            for e in range(n_ens):
                futs.append(svc.kput_many(e, keys[:k // 2], vals))
                futs.append(svc.kget_many(e, keys[k // 2:]))
            while any(svc.queues):
                svc.flush()
            assert all(f.done for f in futs)
            return n_ens * k

        one_round()  # warm (slots, remote compile, sync settled)
        svc.ack_timeout = 10.0
        g0 = dict(svc.stats()["group"])

        # Pipelined measured loop (VERDICT r4 weak #5): keep up to 4
        # rounds in flight so flush N+1's build/ship/local-launch
        # overlaps flush N's replica acks (the windowed PeerLink +
        # deferred commit barrier).  Latency is client-observed:
        # submit -> every future of the round resolved.
        def submit():
            futs = []
            rnd[0] += 1
            for e in range(n_ens):
                if e % stride == rnd[0] % stride:
                    futs.append(svc.kput_many(e, keys[:k // 2], vals))
                futs.append(svc.kget_many(e, keys[k // 2:]))
            return futs

        lat = []
        ops = 0
        inflight = []
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now < t_end and len(inflight) < 4:
                inflight.append((now, submit()))
            svc.flush()
            while inflight and all(f.done for f in inflight[0][1]):
                tb, _futs = inflight.pop(0)
                lat.append(time.perf_counter() - tb)
                # each future is a many-batch of k//2 keys (dense:
                # 2*n_ens batches/round = the seed's n_ens*k count)
                ops += len(_futs) * (k // 2)
            if now >= t_end and (not inflight and lat):
                break
            assert now < t_end + 120.0, "repgroup bench wedged"
        elapsed = time.perf_counter() - t0
        g = svc.stats()["group"]
        assert g["quorum_failures"] == 0, g
        assert g["peers_synced"] == 2, g
        entries = max((g["repl_delta_entries"] + g["repl_full_entries"])
                      - (g0["repl_delta_entries"]
                         + g0["repl_full_entries"]), 1)
        frames = max(g["repl_frames"] - g0["repl_frames"], 1)
        acked = max(g["repl_acked_batches"] - g0["repl_acked_batches"],
                    1)
        out = {
            "ops_per_sec": round(ops / elapsed, 1),
            "p50_ms": round(float(np.percentile(
                np.asarray(lat) * 1e3, 50)), 3),
            "p99_ms": round(float(np.percentile(
                np.asarray(lat) * 1e3, 99)), 3),
            "bytes_per_entry": round(
                (g["repl_bytes_sections"] - g0["repl_bytes_sections"])
                / entries, 1),
            "bytes_full_equiv": round(
                (g["repl_bytes_full_equiv"]
                 - g0["repl_bytes_full_equiv"]) / entries, 1),
            "delta_entries": g["repl_delta_entries"]
            - g0["repl_delta_entries"],
            "full_entries": g["repl_full_entries"]
            - g0["repl_full_entries"],
            "breakdown_ms": {
                "build": round((g["repl_build_s"] - g0["repl_build_s"])
                               / entries * 1e3, 3),
                "encode": round(
                    (g["repl_encode_s"] - g0["repl_encode_s"])
                    / frames * 1e3, 3),
                "ack": round((g["repl_ack_s"] - g0["repl_ack_s"])
                             / acked * 1e3, 3),
            },
        }
        if smoke:
            # delta/full equivalence tripwire: every replica lane's
            # engine state bit-equal to the leader's after drain.
            # Quorum settles at majority, so first wait for every
            # lane to reach the leader's applied position (a slow
            # replica may still be draining its link backlog).
            for _ in range(3):
                svc.heartbeat()
            svc._drain_pending(block_all=True)
            want_pos = (svc.core.applied_ge, svc.core.applied_seq)
            end = time.monotonic() + 60.0
            while time.monotonic() < end:
                done = True
                for s in servers:
                    with s._lock:
                        done = done and ((s.core.applied_ge,
                                          s.core.applied_seq)
                                         >= want_pos)
                if done:
                    break
                time.sleep(0.02)
            d_l = repgroup.dump_state(svc)
            ok = True
            for s in servers:
                with s._lock:
                    d_r = repgroup.dump_state(s.svc)
                ok = ok and d_l[0] == d_r[0]
            out["equivalence_ok"] = ok
        svc.stop()
        return out
    finally:
        for s in servers:
            s.stop()
        for p in procs:
            try:
                p.send_signal(signal.SIGKILL)
            except ProcessLookupError:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def run_fleet_obs_overhead(seconds: float, n_ens: int = 16,
                           n_slots: int = 16, k: int = 8,
                           rounds: int = 3) -> dict:
    """Fleet-federation overhead A/B on the replicated smoke rung
    (acceptance bound: federation pull ON within 2% of OFF — the
    PR 8 op-trace bar).

    The standing watchdog pull is the only fleet-obs cost a serving
    leader pays continuously: every cadence it posts one ``obsq``
    timeline request per link (riding the SAME FIFO socket as the
    apply stream) and harvests the previous window's responses.  The
    A/B: two identical in-process 3-host groups, the ON arm with
    ``RETPU_WATCHDOG=1`` and a deliberately aggressive cadence (8
    flushes — serving defaults evaluate 8x less often, so the bound
    measured here is conservative), the OFF arm ``RETPU_WATCHDOG=0``;
    one long interleaved stream of settled keyed rounds at batch
    granularity with the pair order flipping (the PR 6 methodology —
    window estimators lie on a small box), per-arm medians."""
    import shutil
    import tempfile

    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel import repgroup
    from riak_ensemble_tpu.parallel.batched_host import WallRuntime

    tmp = tempfile.mkdtemp(prefix="bench_fleetobs_")
    packs = []
    keys = [f"key{j}" for j in range(k)]
    vals = [b"v%d" % j for j in range(k // 2)]

    def make(tag: str, env: str):
        servers = [repgroup.ReplicaServer(
            n_ens, 3, n_slots, data_dir=f"{tmp}/{tag}_r{i}",
            config=fast_test_config()) for i in (1, 2)]
        svc = _env_scoped(
            "RETPU_WATCHDOG", env,
            lambda: repgroup.ReplicatedService(
                WallRuntime(), n_ens, 1, n_slots, group_size=3,
                peers=[("127.0.0.1", s.repl_port) for s in servers],
                ack_timeout=60.0, max_ops_per_tick=k,
                config=fast_test_config(),
                data_dir=f"{tmp}/{tag}_leader"))
        repgroup.warmup_kernels(svc)
        assert svc.takeover(), "fleet-obs bench: takeover failed"
        if env == "1":
            # aggressive cadence: the measured arm pulls 8x more
            # often than the serving default — the bound stays
            # conservative
            svc.watchdog.cadence = 8
        pack = {"svc": svc, "servers": servers}
        packs.append(pack)
        batch(pack)  # warm: slots, remote compile, first sync
        svc.ack_timeout = 10.0
        return pack

    def batch(pack) -> float:
        svc = pack["svc"]
        t0 = time.perf_counter()
        futs = []
        for e in range(n_ens):
            futs.append(svc.kput_many(e, keys[:k // 2], vals))
            futs.append(svc.kget_many(e, keys[k // 2:]))
        while any(svc.queues):
            svc.flush()
        assert all(f.done for f in futs), "fleet-obs A/B: unsettled"
        return time.perf_counter() - t0

    try:
        on_pack, off_pack = make("on", "1"), make("off", "0")
        on_t, off_t, n = _interleaved_ab(on_pack, off_pack, batch,
                                         seconds, rounds)
        on_svc, off_svc = on_pack["svc"], off_pack["svc"]
        out = _ab_scores("fleet_obs", on_t, off_t, n, k * n_ens)
        # sanity: the ON arm really pulled (posted obsq sidebands and
        # refreshed at least one link's clock estimate), the OFF arm
        # really didn't — otherwise the A/B measured nothing
        out["fleet_obs_pulls"] = int(on_svc.watchdog.pulls)
        out["fleet_obs_watchdog_evals"] = int(on_svc.watchdog.evals)
        clk = [l.clock.samples for l in on_svc._links]
        out["fleet_obs_clock_samples"] = int(sum(clk))
        assert on_svc.watchdog.pulls > 0, \
            "fleet-obs ON arm never pulled — cadence plumbing broken"
        assert off_svc.watchdog.pulls == 0, \
            "fleet-obs OFF arm pulled despite RETPU_WATCHDOG=0"
        return out
    finally:
        for pack in packs:
            try:
                pack["svc"].stop()
            except Exception:
                pass
            for s in pack["servers"]:
                s.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run_faultsweep(seconds: float, smoke: bool) -> dict:
    """Adversarial fault-injection rungs (docs/ARCHITECTURE.md §13):
    what the system does when the NETWORK or the DISK misbehaves,
    measured instead of asserted.

    1. **RTT sweep** — a live leader + replica host (group of 2, so
       every commit's quorum crosses the injected link) under 0/1/5 ms
       of injected per-link ack RTT, at launch ``pipeline_depth`` 1
       (with a 1-deep ack window and a serial client loop — the
       pre-pipelining world) vs 2 (4-deep window, windowed client).
       The depth-2 arm must WIN once the link is slow: the PR 1/PR 5
       pipelining claims, finally falsifiable on one box.
    2. **Fsync-delay rung** — the keyed WAL'd closed loop with the
       fsync barrier delayed (the slow-disk nemesis): what a slow
       disk costs per op with the flush-batched WAL amortizing it.
    3. **Noisy-tenant rung** — one hot tenant hammering a few rows
       next to many near-idle tenants; the per-tenant attribution
       plane reports the QUIET tenants' p99 with active-column
       compaction on vs off (`quiet_p99_ratio` < 1 = compaction is
       isolating the quiet tenants from the hot tenant's launch
       grid).

    The injected fault config is embedded in the result next to the
    stage's box fingerprint, so a round JSON can never present a
    nemesis number as a clean-box number."""
    n_ens, n_slots, k = (8, 8, 8) if smoke else (32, 16, 16)
    rtts = (0.0, 1.0) if smoke else (0.0, 1.0, 5.0)
    sweep = []
    for rtt in rtts:
        point = {"rtt_ms": rtt}
        for depth in (1, 2):
            r = _faultsweep_rtt_arm(n_ens, n_slots, k, seconds,
                                    depth, rtt)
            point[f"depth{depth}_ops_per_sec"] = r["ops_per_sec"]
            point[f"depth{depth}_p50_ms"] = r["p50_ms"]
            point[f"depth{depth}_p99_ms"] = r["p99_ms"]
        point["depth2_speedup"] = round(
            point["depth2_ops_per_sec"]
            / max(point["depth1_ops_per_sec"], 1e-9), 3)
        sweep.append(point)

    fsync_ms = 2.0
    base = _faultsweep_fsync_arm(n_ens, n_slots, k, seconds, 0.0)
    slow = _faultsweep_fsync_arm(n_ens, n_slots, k, seconds,
                                 fsync_ms)
    fsync = {
        "fsync_delay_ms": fsync_ms,
        "ops_per_sec": slow["ops_per_sec"],
        "baseline_ops_per_sec": base["ops_per_sec"],
        "slowdown": round(base["ops_per_sec"]
                          / max(slow["ops_per_sec"], 1e-9), 3),
        "injected_fsync_delays": slow["fsync_delays"],
    }

    nshape = (16, 8, 8) if smoke else (512, 16, 32)
    noisy_on = _noisy_tenant_arm(*nshape, seconds, compact=True)
    noisy_off = _noisy_tenant_arm(*nshape, seconds, compact=False)
    noisy = {
        "n_ens": nshape[0],
        "hot_ops": noisy_on["hot_ops"],
        "quiet_ops": noisy_on["quiet_ops"],
        "quiet_p99_ms_compact": noisy_on["quiet_p99_ms"],
        "quiet_p99_ms_nocompact": noisy_off["quiet_p99_ms"],
        "hot_p99_ms_compact": noisy_on["hot_p99_ms"],
        "ops_per_sec_compact": noisy_on["ops_per_sec"],
        "ops_per_sec_nocompact": noisy_off["ops_per_sec"],
        "quiet_p99_ratio": round(
            noisy_on["quiet_p99_ms"]
            / max(noisy_off["quiet_p99_ms"], 1e-9), 3),
    }

    # Mesh rung (one shape): the SAME depth-1/2 A/B at the deepest
    # injected-RTT point with the LEADER's engine sharded over the
    # 8-device 'ens' mesh — the pipelining claim must survive sharded
    # serving, not just the single-shard lane.  Gated on the stage
    # environment actually exposing 8 devices (the driver injects
    # XLA_FLAGS for this stage); recorded beside, not folded into,
    # the single-shard headline speedup.
    import jax
    mesh = None
    if not smoke and jax.device_count() >= 8:
        from riak_ensemble_tpu.parallel.mesh import mesh_engine
        engine = mesh_engine(8)
        mrtt = max(rtts)
        mesh = {"rtt_ms": mrtt, "mesh_devices": 8}
        for depth in (1, 2):
            r = _faultsweep_rtt_arm(n_ens, n_slots, k, seconds,
                                    depth, mrtt, engine=engine)
            mesh[f"depth{depth}_ops_per_sec"] = r["ops_per_sec"]
            mesh[f"depth{depth}_p99_ms"] = r["p99_ms"]
        mesh["depth2_speedup"] = round(
            mesh["depth2_ops_per_sec"]
            / max(mesh["depth1_ops_per_sec"], 1e-9), 3)

    # headline = the DEEPEST injected-RTT point (>=1 ms): the claim
    # is "depth 2 wins once the link is slow", and the slowest link
    # is where the overlap signal clears this box's noise floor (at
    # 1 ms the injected delay is under 10% of a batch p50 on the
    # 1-core CPU rung — cross-run noise dominates there; the full
    # per-point sweep rides the JSON either way)
    speedup_deep = next((p["depth2_speedup"] for p in reversed(sweep)
                         if p["rtt_ms"] >= 1.0), None)
    return {
        "faultsweep": {
            "shape": {"n_ens": n_ens, "n_slots": n_slots, "k": k},
            "rtt_sweep": sweep,
            "mesh_rtt": mesh,
            "fsync": fsync,
            "noisy_tenant": noisy,
            # the nemesis that produced these numbers, embedded so
            # the round JSON carries fault config + box fingerprint
            # side by side (acceptance requirement)
            "fault_config": {
                "rtt_ms_points": list(rtts),
                "rtt_side": "ack (replica→leader)",
                "fsync_ms": fsync_ms,
                "knobs": {"RETPU_FAULT_RTT_MS": "<per-link>",
                          "RETPU_FAULT_FSYNC_MS": str(fsync_ms)},
            },
        },
        "faultsweep_depth2_speedup": speedup_deep,
    }


def _faultsweep_rtt_arm(n_ens: int, n_slots: int, k: int,
                        seconds: float, depth: int,
                        rtt_ms: float, engine=None) -> dict:
    """One (pipeline_depth, injected-ack-RTT) point: leader + ONE
    in-process replica host (group of 2 — the replica's ack is on
    every commit path), keyed closed loop, client window matched to
    the depth (1 = fully serial, the pre-PR1 arm).  ``engine`` shards
    the LEADER's lane (the replica host re-executes op planes
    single-shard — host replication is placement-agnostic)."""
    import shutil
    import tempfile

    from riak_ensemble_tpu import faults
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel import repgroup
    from riak_ensemble_tpu.parallel.batched_host import WallRuntime

    tmp = tempfile.mkdtemp(prefix="bench_faultsweep_")
    server = None
    svc = None
    try:
        server = repgroup.ReplicaServer(
            n_ens, 2, n_slots, data_dir=f"{tmp}/r1",
            config=fast_test_config())
        svc = repgroup.ReplicatedService(
            WallRuntime(), n_ens, 1, n_slots, group_size=2,
            peers=[("127.0.0.1", server.repl_port)],
            ack_timeout=60.0, max_ops_per_tick=k,
            config=fast_test_config(), data_dir=tmp + "/leader",
            pipeline_depth=depth,
            repl_window=(1 if depth == 1 else 4),
            engine=engine)
        repgroup.warmup_kernels(svc)
        assert svc.takeover(), "faultsweep: takeover failed"
        keys = [f"key{j}" for j in range(k)]
        vals = [b"v%d" % j for j in range(k // 2)]

        def submit():
            futs = []
            for e in range(n_ens):
                futs.append(svc.kput_many(e, keys[:k // 2], vals))
                futs.append(svc.kget_many(e, keys[k // 2:]))
            return futs

        futs = submit()  # warm: slots, elections, remote ladder
        while any(svc.queues):
            svc.flush()
        assert all(f.done for f in futs)
        svc.ack_timeout = 30.0

        plan = faults.install(faults.FaultPlan())
        if rtt_ms > 0.0:
            for link in svc._links:
                plan.set_rtt(link.label, faults.LOCAL, rtt_ms)

        window = 1 if depth == 1 else 4
        lat = []
        ops = 0
        inflight = []
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now < t_end and len(inflight) < window:
                inflight.append((now, submit()))
            svc.flush()
            while inflight and all(f.done for f in inflight[0][1]):
                tb, done = inflight.pop(0)
                lat.append(time.perf_counter() - tb)
                ops += len(done) * (k // 2)
            if now >= t_end and not inflight and lat:
                break
            assert now < t_end + 120.0, "faultsweep arm wedged"
        elapsed = time.perf_counter() - t0
        injected = dict(plan.counters())
        faults.clear()
        out = {
            "ops_per_sec": round(ops / elapsed, 1),
            "p50_ms": round(float(np.percentile(
                np.asarray(lat) * 1e3, 50)), 3),
            "p99_ms": round(float(np.percentile(
                np.asarray(lat) * 1e3, 99)), 3),
            "injected": injected,
        }
        svc.stop()
        svc = None
        return out
    finally:
        faults.clear()
        if svc is not None:
            try:
                svc.stop()
            except Exception:
                pass
        if server is not None:
            server.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _faultsweep_fsync_arm(n_ens: int, n_slots: int, k: int,
                          seconds: float, fsync_ms: float) -> dict:
    """Keyed WAL'd closed loop under injected fsync delay (0 = the
    clean baseline arm)."""
    import shutil
    import tempfile

    from riak_ensemble_tpu import faults
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime)

    tmp = tempfile.mkdtemp(prefix="bench_fsync_")
    svc = None
    try:
        svc = BatchedEnsembleService(WallRuntime(), n_ens, 1,
                                     n_slots, tick=None,
                                     max_ops_per_tick=k,
                                     data_dir=tmp)
        keys = [f"key{j}" for j in range(k // 2)]
        vals = [b"v%d" % j for j in range(k // 2)]

        def round_once():
            futs = [svc.kput_many(e, keys, vals)
                    for e in range(n_ens)]
            while not all(f.done for f in futs):
                svc.flush()
            return n_ens * (k // 2)

        round_once()  # warm
        plan = faults.install(faults.FaultPlan())
        if fsync_ms > 0.0:
            plan.set_fsync_delay(fsync_ms)
        ops = 0
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or ops == 0:
            ops += round_once()
        elapsed = time.perf_counter() - t0
        delays = plan.fsync_delays
        faults.clear()
        out = {"ops_per_sec": round(ops / elapsed, 1),
               "fsync_delays": int(delays)}
        svc.stop()
        svc = None
        return out
    finally:
        faults.clear()
        if svc is not None:
            try:
                svc.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _noisy_tenant_arm(n_ens: int, n_slots: int, k: int,
                      seconds: float, compact: bool,
                      guard: bool = False) -> dict:
    """One hot tenant hammering 8 rows every round vs 8 near-idle
    quiet tenants (one small op per round, rotating) — the
    noisy-neighbor shape.  Reports the per-tenant p99s from the
    attribution plane; the caller A/Bs compaction on/off (and, for
    the autotune rung, the controller's admission guard on/off)."""
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime)

    svc = BatchedEnsembleService(WallRuntime(), n_ens, 1, n_slots,
                                 tick=None, max_ops_per_tick=k)
    try:
        if not compact:
            svc._compact = False  # the RETPU_COMPACT=0 arm
        if guard:
            # arm the controller's tenant-admission actuator with a
            # bench-tight cadence/threshold (the svc._compact idiom)
            svc.set_autotune(True)
            svc.controller.cadence = 8
            svc.controller.guard.min_ops = 16
        hot_n = min(8, n_ens // 2)
        hot_rows = list(range(hot_n))
        quiet_rows = list(range(hot_n, min(hot_n + 8, n_ens)))
        for e in hot_rows:
            svc.set_tenant_label(e, "hot")
        for i, e in enumerate(quiet_rows):
            svc.set_tenant_label(e, f"quiet{i}")
        keys = [f"key{j}" for j in range(k // 2)]
        vals = [b"v%d" % j for j in range(k // 2)]
        qi = [0]

        def round_once():
            futs = [svc.kput_many(e, keys, vals) for e in hot_rows]
            qe = quiet_rows[qi[0] % len(quiet_rows)]
            qi[0] += 1
            futs.append(svc.kput(qe, "qk", b"qv"))
            futs.append(svc.kget(qe, "qk"))
            while not all(f.done for f in futs):
                svc.flush()
            return hot_n * (k // 2) + 2

        for _ in range(3):
            round_once()  # warm: slots + the compiled (K, A) shapes
        # zero the attribution planes so warmup compiles don't ride
        # the measured p99 (bench-local reset; the plane itself has
        # no reset verb by design — recycle clears per-row)
        svc._tenant_lat[:] = 0
        svc.tenant_ops[:] = 0
        ops = 0
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or ops == 0:
            ops += round_once()
        elapsed = time.perf_counter() - t0
        ts = svc.tenant_stats(top=32)
        quiet = [v for lbl, v in ts.items()
                 if lbl.startswith("quiet") and v["ops"] > 0]
        assert quiet, ts
        out = {
            "ops_per_sec": round(ops / elapsed, 1),
            "hot_ops": ts.get("hot", {}).get("ops", 0),
            "quiet_ops": int(sum(v["ops"] for v in quiet)),
            "hot_p99_ms": ts.get("hot", {}).get("p99_ms"),
            "quiet_p99_ms": round(float(np.median(
                [v["p99_ms"] for v in quiet])), 3),
        }
        if guard:
            out["guard_decisions"] = [
                ev for ev in svc.controller.journal.snapshot()
                if ev["actuator"] == "tenant_guard"]
            out["throttled_rows"] = {
                lbl: rows for lbl, rows in
                svc.controller.guard.throttled.items()}
        return out
    finally:
        svc.stop()


def run_autotune(seconds: float, smoke: bool) -> dict:
    """The controller A/B (docs/ARCHITECTURE.md §14): does the
    obs-actuated runtime controller FIND the link-dependent optimum
    the PR 9 faultsweep proved exists, and is every knob change it
    makes reconstructible from its journal alone?

    Per injected-ack-RTT point (0 ms = the clean link, 5 ms = the
    slow link where depth 2 measured 1.222x): two STATIC arms
    (depth 1 / window 1 and depth 2 / window 4 — the candidate
    optima) and one CONTROLLER arm that starts at depth 1 / window 1
    with ``RETPU_AUTOTUNE`` armed, adapts for the first part of the
    budget, then measures steady state.  Acceptance (round time, not
    smoke): the controller arm within 5% of the best static arm at
    EVERY point.  Both modes assert the journal property: replaying
    the decision journal over the initial knobs must land exactly on
    the live knobs, and the ``retpu_autotune_*`` gauges must agree —
    the self-tuning is auditable, not just present.

    Plus the tenant-guard rung: the PR 9 noisy-tenant shape with the
    guard armed vs not — the journal must show the admission
    decision and the quiet tenants' p99 must not degrade."""
    n_ens, n_slots, k = (8, 8, 8) if smoke else (32, 16, 16)
    rtts = (0.0, 2.0) if smoke else (0.0, 5.0)
    points = []
    worst_ratio = None
    for rtt in rtts:
        statics = {}
        for depth, window in ((1, 1), (2, 4)):
            r = _faultsweep_rtt_arm(n_ens, n_slots, k, seconds,
                                    depth, rtt)
            statics[f"depth{depth}_win{window}"] = r["ops_per_sec"]
        ctrl = _autotune_controller_arm(n_ens, n_slots, k, seconds,
                                        rtt)
        best = max(statics.values())
        ratio = round(ctrl["ops_per_sec"] / max(best, 1e-9), 3)
        worst_ratio = (ratio if worst_ratio is None
                       else min(worst_ratio, ratio))
        points.append({
            "rtt_ms": rtt,
            "static_ops_per_sec": statics,
            "controller_ops_per_sec": ctrl["ops_per_sec"],
            "controller_final": ctrl["final"],
            "controller_decisions": ctrl["decisions"],
            "journal_reconstructed": ctrl["journal_reconstructed"],
            "vs_best_static": ratio,
        })
    # Mesh point (one shape): the controller vs the static candidates
    # at the slow-link RTT with the leader's engine sharded over the
    # 8-device 'ens' mesh — the depth actuator must find the same
    # optimum when the lane it tunes is mesh-sharded.  Recorded
    # beside, not folded into, the single-shard worst_ratio headline.
    import jax
    mesh = None
    if not smoke and jax.device_count() >= 8:
        from riak_ensemble_tpu.parallel.mesh import mesh_engine
        engine = mesh_engine(8)
        mrtt = max(rtts)
        statics = {}
        for depth, window in ((1, 1), (2, 4)):
            r = _faultsweep_rtt_arm(n_ens, n_slots, k, seconds,
                                    depth, mrtt, engine=engine)
            statics[f"depth{depth}_win{window}"] = r["ops_per_sec"]
        ctrl = _autotune_controller_arm(n_ens, n_slots, k, seconds,
                                        mrtt, engine=engine)
        mesh = {
            "rtt_ms": mrtt,
            "mesh_devices": 8,
            "static_ops_per_sec": statics,
            "controller_ops_per_sec": ctrl["ops_per_sec"],
            "controller_final": ctrl["final"],
            "journal_reconstructed": ctrl["journal_reconstructed"],
            "vs_best_static": round(
                ctrl["ops_per_sec"]
                / max(max(statics.values()), 1e-9), 3),
        }

    guard = _autotune_guard_arm(
        *((16, 8, 8) if smoke else (512, 16, 32)), seconds)
    return {
        "autotune": {
            "shape": {"n_ens": n_ens, "n_slots": n_slots, "k": k},
            "points": points,
            "mesh_point": mesh,
            "tenant_guard": guard,
        },
        "autotune_vs_best_static": worst_ratio,
    }


def _autotune_controller_arm(n_ens: int, n_slots: int, k: int,
                             seconds: float, rtt_ms: float,
                             engine=None) -> dict:
    """The controller arm of the autotune A/B: the faultsweep
    leader + replica-host shape, starting at depth 1 / window 1 with
    the controller armed (tight cadence so it converges inside a
    bench budget), adaptation phase then steady-state measurement.
    Asserts the journal-reconstruction property before returning."""
    import shutil
    import tempfile

    from riak_ensemble_tpu import faults
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.obs.controller import replay
    from riak_ensemble_tpu.parallel import repgroup
    from riak_ensemble_tpu.parallel.batched_host import WallRuntime

    tmp = tempfile.mkdtemp(prefix="bench_autotune_")
    server = None
    svc = None
    try:
        server = repgroup.ReplicaServer(
            n_ens, 2, n_slots, data_dir=f"{tmp}/r1",
            config=fast_test_config())
        svc = repgroup.ReplicatedService(
            WallRuntime(), n_ens, 1, n_slots, group_size=2,
            peers=[("127.0.0.1", server.repl_port)],
            ack_timeout=60.0, max_ops_per_tick=k,
            config=fast_test_config(), data_dir=tmp + "/leader",
            pipeline_depth=1, repl_window=1, engine=engine)
        repgroup.warmup_kernels(svc)
        assert svc.takeover(), "autotune arm: takeover failed"
        svc.set_autotune(True)
        # bench-local controller tuning (the svc._compact idiom):
        # a tight cadence so convergence fits a bench budget
        svc.controller.cadence = 8
        initial = {"pipeline_depth": svc.pipeline_depth,
                   "repl_window": svc.repl_window}
        keys = [f"key{j}" for j in range(k)]
        vals = [b"v%d" % j for j in range(k // 2)]

        def submit():
            futs = []
            for e in range(n_ens):
                futs.append(svc.kput_many(e, keys[:k // 2], vals))
                futs.append(svc.kget_many(e, keys[k // 2:]))
            return futs

        futs = submit()  # warm: slots, elections, remote ladder
        while any(svc.queues):
            svc.flush()
        assert all(f.done for f in futs)
        svc.ack_timeout = 30.0
        plan = faults.install(faults.FaultPlan())
        if rtt_ms > 0.0:
            for link in svc._links:
                plan.set_rtt(link.label, faults.LOCAL, rtt_ms)

        def closed_loop(budget_s: float) -> tuple:
            # window follows the LIVE depth so a controller step
            # changes the offered concurrency exactly like the
            # matching static arm's client would
            lat = []
            ops = 0
            inflight = []
            t_end = time.perf_counter() + max(budget_s, 1e-3)
            t0 = time.perf_counter()
            while True:
                now = time.perf_counter()
                window = 1 if svc.pipeline_depth == 1 else 4
                if now < t_end and len(inflight) < window:
                    inflight.append((now, submit()))
                svc.flush()
                while inflight and all(f.done
                                       for f in inflight[0][1]):
                    tb, done = inflight.pop(0)
                    lat.append(time.perf_counter() - tb)
                    ops += len(done) * (k // 2)
                if now >= t_end and not inflight and lat:
                    break
                assert now < t_end + 120.0, "autotune arm wedged"
            return ops, time.perf_counter() - t0

        # adaptation phase: give the controller a few cadence
        # windows to converge, then measure steady state
        closed_loop(max(seconds * 0.6, 0.2))
        ops, elapsed = closed_loop(max(seconds, 1e-3))
        faults.clear()
        journal = svc.controller.journal.snapshot()
        final = {"pipeline_depth": svc.pipeline_depth,
                 "repl_window": svc.repl_window}
        # the acceptance property: the journal ALONE reconstructs
        # the live knobs, and the gauges tell the same story
        reconstructed = replay(
            [ev for ev in journal
             if ev.get("knob") in ("pipeline_depth", "repl_window")],
            initial)
        assert reconstructed == final, (reconstructed, final, journal)
        snap = svc.obs_registry.snapshot()
        assert snap["retpu_autotune_pipeline_depth"] \
            == final["pipeline_depth"], snap
        assert snap["retpu_autotune_repl_window"] \
            == final["repl_window"], snap
        assert snap["retpu_autotune_decisions_total"] \
            == svc.controller.journal.total
        out = {
            "ops_per_sec": round(ops / elapsed, 1),
            "final": final,
            "decisions": journal,
            "journal_reconstructed": True,
        }
        svc.stop()
        svc = None
        return out
    finally:
        faults.clear()
        if svc is not None:
            try:
                svc.stop()
            except Exception:
                pass
        if server is not None:
            server.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _autotune_guard_arm(n_ens: int, n_slots: int, k: int,
                        seconds: float) -> dict:
    """The tenant-guard rung: the PR 9 noisy-tenant shape with the
    controller's admission guard armed vs the unguarded baseline.
    The guard must journal an admission decision against the hot
    tenant, and the quiet tenants' p99 must not degrade under it."""
    base = _noisy_tenant_arm(n_ens, n_slots, k, seconds,
                             compact=True)
    guarded = _noisy_tenant_arm(n_ens, n_slots, k, seconds,
                                compact=True, guard=True)
    assert guarded["guard_decisions"], \
        "tenant guard armed but never journaled a decision"
    return {
        "quiet_p99_ms_guarded": guarded["quiet_p99_ms"],
        "quiet_p99_ms_unguarded": base["quiet_p99_ms"],
        "quiet_p99_ratio": round(
            guarded["quiet_p99_ms"]
            / max(base["quiet_p99_ms"], 1e-9), 3),
        "hot_ops_guarded": guarded["hot_ops"],
        "hot_ops_unguarded": base["hot_ops"],
        "ops_per_sec_guarded": guarded["ops_per_sec"],
        "ops_per_sec_unguarded": base["ops_per_sec"],
        "guard_decisions": guarded["guard_decisions"],
        "throttled_rows": guarded["throttled_rows"],
    }


def _make_workload(n_ens: int, n_peers: int, n_slots: int, k: int):
    """Shared kernel-stage workload: elected engine state + one fixed
    [K, E] op plane (seed 0).  Used by BOTH the throughput stage and
    the stepprobe so the stepprobe's budget calibration measures the
    same computation the stages will run."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    state = eng.init_state(n_ens, n_peers, n_slots)
    up = jnp.ones((n_ens, n_peers), bool)
    state, won = eng.elect_step(
        state, jnp.ones((n_ens,), bool), jnp.zeros((n_ens,), jnp.int32), up)
    jax.block_until_ready(state)

    rng = np.random.default_rng(0)
    kind = jnp.asarray(rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)),
                       jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (k, n_ens)), jnp.int32)
    val = jnp.asarray(rng.integers(1, 1 << 20, (k, n_ens)), jnp.int32)
    lease_ok = jnp.ones((k, n_ens), bool)
    return eng, state, won, up, kind, slot, val, lease_ok


def run(n_ens: int, n_peers: int, n_slots: int, k: int,
        seconds: float) -> float:
    import jax

    eng, state, won, up, kind, slot, val, lease_ok = _make_workload(
        n_ens, n_peers, n_slots, k)

    # Compile + warm up.  NOTE: no device→host transfers before or
    # inside the timed region — on the tunneled single-chip platform a
    # d2h copy permanently degrades subsequent dispatches to a ~2 ms
    # synchronous path (measured 40x); correctness checks run AFTER
    # the timed loop instead.
    state2, _res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state2)

    # Calibrate per-step time (blocked, so it includes sync overhead —
    # a conservative estimate) to bound the enqueue depth: async
    # dispatch outruns the device by orders of magnitude, and an
    # unbounded wall-clock enqueue loop would queue minutes of drain.
    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        state, res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
        jax.block_until_ready(state)
    step_est = (time.perf_counter() - t0) / ncal

    # Timed loop: a bounded number of chained steps; ops advance real
    # protocol state.  The final block waits for every queued step, so
    # `elapsed` covers full execution, not just enqueue.
    iters = max(10, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    # Post-loop correctness: elections all won; every op in the last
    # step acked (puts committed / gets served or lease-bypassed).
    assert bool(np.asarray(won).all()), "bench: elections failed"
    ok = np.asarray(res.committed | res.get_ok | (np.asarray(kind) == 0))
    assert ok.all(), "bench: ops failed"
    return n_ens * k * iters / elapsed


def run_stepprobe(n_ens: int, n_peers: int, n_slots: int, k: int,
                  n_steps: int = 5) -> dict:
    """Single-launch latency evidence for a flickering accelerator.

    Observed round 4: the tunneled TPU answered the preflight probe,
    compiled every stage kernel (persistent cache confirms), then
    executed launches so slowly that every throughput stage blew its
    budget — and the tunnel died again ~50 min later.  The
    calibrate-then-loop stages need tens of sequential launches; this
    stage instead times INDIVIDUAL kv_step_scan launches and persists
    each measurement the moment it exists (``RETPU_STEPPROBE_OUT``),
    so even ONE completed step inside an alive-window yields an
    honest, conservative (sync-overhead-included) throughput figure:
    ``n_ens * k / step_s``.
    """
    import jax

    out_path = os.environ.get("RETPU_STEPPROBE_OUT")
    partial: dict = {"n_ens": n_ens, "k": k,
                     "platform": jax.devices()[0].platform}

    def persist() -> None:
        # Atomic replace: the parent kills this process with SIGKILL
        # on timeout, and a torn in-place write would corrupt the very
        # measurements this file exists to save.
        if out_path:
            tmp = out_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(partial, f)
            os.replace(tmp, out_path)

    persist()
    t0 = time.perf_counter()
    eng, state, _won, up, kind, slot, val, lease_ok = _make_workload(
        n_ens, n_peers, n_slots, k)
    partial["init_elect_s"] = time.perf_counter() - t0
    persist()

    t0 = time.perf_counter()
    state, _ = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state)
    partial["first_step_s"] = time.perf_counter() - t0  # includes compile
    persist()

    steps: list = []
    partial["steps_s"] = steps
    for _ in range(n_steps):
        t0 = time.perf_counter()
        state, _ = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
        jax.block_until_ready(state)
        steps.append(time.perf_counter() - t0)
        persist()
    med = sorted(steps)[len(steps) // 2]
    partial["median_step_s"] = med
    partial["single_step_ops_per_sec"] = n_ens * k / med
    persist()
    return partial


#: the shape single-launch TPU evidence is gathered at (matches the
#: full ladder's headline shape) — shared with tpu_attempt.py.
STEPPROBE_SHAPES = dict(n_ens=10_000, n_peers=5, n_slots=128, k=64)


def _run_stepprobe(timeout: float, shapes: dict) -> "dict | None":
    """Run the stepprobe stage in a killable subprocess, recovering
    PARTIAL measurements (steps persisted before a timeout kill) via
    the RETPU_STEPPROBE_OUT side file.  A subprocess that silently
    landed on CPU (tunnel died between the caller's preflight and the
    probe — not TPU evidence) comes back as
    ``{"error": ..., "cpu_fallback": True}``."""
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    try:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--stage", "stepprobe"]
        for f, v in shapes.items():
            cmd += [f"--{f.replace('_', '-')}", str(v)]
        result, err = _spawn_stage(
            cmd, timeout, env=dict(os.environ, RETPU_STEPPROBE_OUT=path))
        if result is not None:
            if result.get("platform") == "cpu":
                return {"error": "stepprobe subprocess landed on cpu "
                                 "(accelerator gone)",
                        "cpu_fallback": True}
            return result
        try:
            with open(path) as f:
                partial = json.load(f)
            partial["spawn_error"] = err
        except (OSError, json.JSONDecodeError):
            # No side file at all — preserve WHY (timeout vs crash) so
            # a dead round is triageable from the emitted JSON.
            return {"error": err}
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    if partial.get("platform") == "cpu":
        return {"error": "stepprobe subprocess landed on cpu "
                         "(accelerator gone)", "cpu_fallback": True}
    steps = partial.get("steps_s") or []
    if not steps and "first_step_s" not in partial:
        return partial  # died before any launch completed; keep why
    partial["partial"] = True
    if steps:
        med = sorted(steps)[len(steps) // 2]
        partial["median_step_s"] = med
        partial["single_step_ops_per_sec"] = (
            partial["n_ens"] * partial["k"] / med)
    return partial


def run_widecmp(n_ens: int, n_peers: int, n_slots: int, k: int,
                seconds: float) -> dict:
    """Wide-scheduling A/B: the SAME distinct-slot op plane through a
    scalar-scan service and a wide (RETPU_WIDE-style) service, one
    process, same workload both arms.  Distinct slots per ensemble
    guarantee the wide arm really takes the wide path (asserted via
    wide_launches) — random slots would chain past the G<=2 gate and
    silently compare scalar against scalar."""
    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime)

    assert k <= n_slots, \
        f"distinct-slot plane needs k <= n_slots ({k} > {n_slots})"
    rng = np.random.default_rng(0)
    kind = rng.choice([eng.OP_PUT, eng.OP_GET],
                      (k, n_ens)).astype(np.int32)
    slot = np.stack([rng.permutation(n_slots)[:k]
                     for _ in range(n_ens)], axis=1).astype(np.int32)
    val = rng.integers(1, 1 << 20, (k, n_ens), dtype=np.int32)

    out: dict = {}
    for wide in (False, True):
        svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                     n_slots, tick=None,
                                     max_ops_per_tick=k)
        svc._wide = wide
        # Warm the exact programs this arm launches (first call also
        # runs the elections fold-in).
        svc.execute(kind, slot, val)
        svc.execute(kind, slot, val)
        t_end = time.perf_counter() + seconds
        iters = 0
        t0 = time.perf_counter()
        while time.perf_counter() < t_end or not iters:
            svc.execute(kind, slot, val)
            iters += 1
        elapsed = time.perf_counter() - t0
        if wide:
            assert svc.wide_launches > 0, \
                "wide arm never took the wide path"
        out["wide_ops_per_sec" if wide else "scalar_ops_per_sec"] = (
            n_ens * k * iters / elapsed)
        svc.stop()
    out["wide_speedup"] = (out["wide_ops_per_sec"]
                           / out["scalar_ops_per_sec"])
    return out


#: internal wall budget for the tpuprobe stage — under the driver's
#: 600 s stage timeout so the probe trims its own tail (ladder rungs,
#: A/B arms) instead of being SIGKILLed mid-measurement.
_TPUPROBE_BUDGET_S = 520.0


def run_tpuprobe(seconds: float) -> dict:
    """Staged live-window probe (ROADMAP TPU re-attempt staging).

    A flickering accelerator window must be spent in strict order so
    even a short window yields evidence: (a) ONE tiny fused step,
    individually timed; (b) the CompileWatch ledger from a full
    service warmup — a blown budget then reads "N named compiles cost
    X s", not "timeout"; (c) the ascending step ladder toward the
    headline shape; (d) the Pallas-quorum and wide-scheduling A/Bs
    with their mechanical keep/kill verdicts (Pallas: KEEP iff >= 10%
    fused-step win at any ladder shape with bit-equal results; wide:
    KEEP iff >= 1.2x on the distinct-slot widecmp rung — both
    TPU-gated, so a CPU box reports "pending-tpu" alongside its
    measured numbers; the wiring itself is rehearsed end to end).

    The Pallas arms run as SUBPROCESSES: ``RETPU_PALLAS_QUORUM`` binds
    at engine-module import, so an in-process A/B would silently
    compare the same path against itself.
    """
    import jax

    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime)

    platform = jax.devices()[0].platform
    deadline = time.perf_counter() + _TPUPROBE_BUDGET_S

    def remaining() -> float:
        return deadline - time.perf_counter()

    out: dict = {"staging": ["tiny_step", "compile_ledger", "ladder",
                             "pallas_ab", "wide_ab"]}

    # (a) one tiny fused step, each launch timed individually — the
    # cheapest possible "is the chip actually executing" evidence.
    tiny = run_stepprobe(64, 3, 16, 4, n_steps=3)
    out["tiny_step"] = {k: tiny[k] for k in
                        ("init_elect_s", "first_step_s",
                         "median_step_s", "single_step_ops_per_sec")}

    # (b) the compile ledger: a full small-shape service warmup with
    # every named compile's cost captured via CompileWatch.
    svc = BatchedEnsembleService(WallRuntime(), 256, 5, 32, tick=None)
    try:
        t0 = time.perf_counter()
        svc.warmup()
        ledger = list(svc._compile_log)
        out["compile_ledger"] = {
            "warmup_s": round(time.perf_counter() - t0, 3),
            "compiles": len(ledger),
            "compile_ms_total": round(
                sum(e["compile_ms"] for e in ledger), 1),
            "slowest": [
                {"fn": e["fn"], "ms": round(e["compile_ms"], 1)}
                for e in sorted(ledger, key=lambda e: e["compile_ms"],
                                reverse=True)[:5]],
        }
    finally:
        svc.stop()

    # (c) ascending ladder toward the headline stepprobe shape; each
    # rung gated on remaining budget so a slow chip still reports the
    # rungs it finished.
    out["ladder"] = []
    for shape in ((1024, 5, 64, 16), (4096, 5, 64, 32),
                  tuple(STEPPROBE_SHAPES.values())):
        if remaining() < 90.0:
            out["ladder_truncated"] = True
            break
        p = run_stepprobe(*shape, n_steps=3)
        out["ladder"].append({k: p[k] for k in
                              ("n_ens", "k", "first_step_s",
                               "median_step_s",
                               "single_step_ops_per_sec")})

    # (d1) Pallas-quorum A/B: kernel-stage subprocesses with the knob
    # in the environment, plus an in-process bit-equality check (the
    # kernel interprets on CPU, so equality is checkable everywhere).
    ab_shape = dict(n_ens=4096, n_peers=5, n_slots=64, k=16)
    arm_secs = min(seconds, 3.0)
    pallas_ab: dict = {}
    for name, knob in (("pallas", "1"), ("jnp", "0")):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--stage", "kernel", "--seconds", str(arm_secs)]
        for f, v in ab_shape.items():
            cmd += [f"--{f.replace('_', '-')}", str(v)]
        if platform == "cpu":
            cmd.append("--force-cpu")
        r, err = _spawn_stage(
            cmd, max(30.0, min(remaining(), 240.0)),
            env=dict(os.environ, RETPU_PALLAS_QUORUM=knob))
        pallas_ab[f"{name}_rounds_per_sec"] = (
            r["kernel_rounds_per_sec"] if r else None)
        if err is not None:
            pallas_ab[f"{name}_error"] = err
    try:
        import jax.numpy as jnp

        from riak_ensemble_tpu.ops.pallas_quorum import (
            quorum_met_epallas)
        from riak_ensemble_tpu.ops.quorum import quorum_met_batch

        rng = np.random.default_rng(7)
        e, v, m = 512, 2, 5
        ack = jnp.asarray(rng.random((e, m)) < 0.5)
        heard = ack | jnp.asarray(rng.random((e, m)) < 0.3)
        vm = np.zeros((e, v, m), bool)
        vm[:, 0, :] = True
        vm[::3, 1, :3] = True  # a second active (joint) view
        vm = jnp.asarray(vm)
        nack = heard & ~ack
        ref = quorum_met_batch(ack, nack, vm,
                               jnp.full((e,), -1, jnp.int32),
                               required="quorum", axis_name=None)
        pal = quorum_met_epallas(ack, nack, vm)
        pallas_ab["bitequal"] = bool(
            (np.asarray(ref) == np.asarray(pal)).all())
    except Exception as exc:  # honest: record, don't crash the probe
        pallas_ab["bitequal"] = None
        pallas_ab["bitequal_error"] = f"{type(exc).__name__}: {exc}"
    p_on = pallas_ab.get("pallas_rounds_per_sec")
    p_off = pallas_ab.get("jnp_rounds_per_sec")
    pallas_ab["speedup"] = (round(p_on / p_off, 3)
                            if p_on and p_off else None)
    out["pallas_ab"] = pallas_ab
    if platform == "cpu":
        out["pallas_verdict"] = "pending-tpu"
        out["pallas_verdict_reason"] = (
            "KEEP iff >=10% fused-step win with bit-equal results, "
            "on TPU; CPU numbers recorded above")
    elif pallas_ab["speedup"] is None:
        out["pallas_verdict"] = "kill"
        out["pallas_verdict_reason"] = ("an A/B arm failed on the "
                                        "live accelerator")
    else:
        keep = (pallas_ab["speedup"] >= 1.10
                and pallas_ab.get("bitequal") is True)
        out["pallas_verdict"] = "keep" if keep else "kill"
        out["pallas_verdict_reason"] = (
            f"speedup={pallas_ab['speedup']} "
            f"bitequal={pallas_ab.get('bitequal')} vs the "
            ">=1.10-with-bit-equality bar")

    # (d2) wide-scheduling A/B: in-process (the wide path is a
    # service attribute, not an import-time knob).
    try:
        wide = run_widecmp(1024, 5, 64, 16, arm_secs)
        out["wide_ab"] = {k: round(v, 1) if "per_sec" in k
                          else round(v, 3)
                          for k, v in wide.items()}
        wide_speedup = wide["wide_speedup"]
    except Exception as exc:
        out["wide_ab"] = {"error": f"{type(exc).__name__}: {exc}"}
        wide_speedup = None
    if platform == "cpu":
        out["wide_verdict"] = "pending-tpu"
        out["wide_verdict_reason"] = (
            "KEEP iff >=1.2x on the distinct-slot widecmp rung on "
            "TPU; CPU numbers recorded above")
    elif wide_speedup is None:
        out["wide_verdict"] = "kill"
        out["wide_verdict_reason"] = ("widecmp failed on the live "
                                      "accelerator")
    else:
        out["wide_verdict"] = ("keep" if wide_speedup >= 1.2
                               else "kill")
        out["wide_verdict_reason"] = (
            f"wide_speedup={round(wide_speedup, 3)} vs the 1.2x bar")
    return out


def run_recovery(seconds: float, smoke: bool) -> dict:
    """``--stage recovery`` (docs/ARCHITECTURE.md §15): restart-to-
    serving time at the 512-ens rung — the RTO half of the crash
    contract, measured, not asserted.

    Build a durable (fsync-WAL) service, ack a keyed working set,
    checkpoint it, ack a WAL tail BEYOND the checkpoint, then release
    the handles with no cleanup (the crash analog) and time the
    restart: ``restore()`` (orbax checkpoint load + host-blob read +
    WAL replay) and the first served read (first-flush warmup /
    compile) are reported separately so a regression names its phase.
    ``recovery_ms`` is the headline the round JSON and the
    ``bench_trend`` ``recov_ms`` column carry.  ``seconds`` scales
    the WAL-tail depth (~seconds/3 rounds of tail keys), so the
    default 3 s budget reproduces the recorded shape exactly and a
    deeper budget measures a deeper replay."""
    import shutil
    import tempfile

    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    n_ens, n_peers, n_slots, k = ((16, 3, 8, 4) if smoke
                                  else (512, 5, 64, 16))
    ckpt_keys = tail_keys = 2 if smoke else 16
    # distinct keys per round; bounded by the slot grid (ckpt keys +
    # tail rounds must all fit per ensemble)
    tail_rounds = min(max(1, int(round(seconds / 3.0))),
                      (n_slots - ckpt_keys) // tail_keys)
    d = tempfile.mkdtemp(prefix="retpu_recovery_")
    try:
        svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                     n_slots, tick=None,
                                     max_ops_per_tick=k, data_dir=d)

        def put_round(tag: str, n: int) -> None:
            keys = [f"{tag}{j}" for j in range(n)]
            vals = [b"v-%s-%d" % (tag.encode(), j) for j in range(n)]
            futs = [svc.kput_many(e, keys, vals)
                    for e in range(n_ens)]
            while any(svc.queues):
                svc.flush()
            assert all(f.done for f in futs), "recovery: unsettled"

        put_round("c", ckpt_keys)
        svc.save()
        for r in range(tail_rounds):
            put_round("t" if r == 0 else f"t{r}x", tail_keys)
        wal_records = svc._wal.count
        svc.stop()
        svc._wal.close()

        t0 = time.perf_counter()
        svc2 = BatchedEnsembleService.restore(
            WallRuntime(), d, tick=None, max_ops_per_tick=k,
            data_dir=d)
        t_restore = time.perf_counter()
        f = svc2.kget(0, "t0")
        while not f.done:
            svc2.flush()
        t_serve = time.perf_counter()
        assert f.value == ("ok", b"v-t-0"), f.value
        svc2.stop()
        return {
            "recovery_ms": round((t_serve - t0) * 1e3, 3),
            "recovery_restore_ms": round((t_restore - t0) * 1e3, 3),
            "recovery_first_op_ms": round((t_serve - t_restore) * 1e3,
                                          3),
            "recovery_wal_records": int(wal_records),
            "recovery_shape": {"n_ens": n_ens, "n_peers": n_peers,
                               "n_slots": n_slots},
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def run_merkle(seconds: float, smoke: bool) -> dict:
    """BASELINE ladder #4: incremental updates into a 1M-segment
    Merkle tree (the always-up-to-date write-path hashing)."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import hash as hashk

    segs = 16 ** 3 if smoke else 16 ** 5
    batch = 256 if smoke else 4096
    rng = np.random.default_rng(0)
    leaves = jnp.zeros((segs, hashk.LANES), jnp.uint32)
    levels = hashk.build(leaves, width=16)
    ids = jnp.asarray(rng.integers(0, segs, batch))
    new = jnp.asarray(rng.integers(0, 2 ** 32, (batch, hashk.LANES),
                                   dtype=np.uint32))
    levels = hashk.update(levels, ids, new, width=16)
    jax.block_until_ready(levels)

    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        levels = hashk.update(levels, ids, new, width=16)
        jax.block_until_ready(levels)
    step_est = (time.perf_counter() - t0) / ncal
    iters = max(10, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        levels = hashk.update(levels, ids, new, width=16)
    jax.block_until_ready(levels)
    elapsed = time.perf_counter() - t0
    rate = batch * iters / elapsed
    return {
        "metric": f"merkle_key_updates_per_sec_{segs}_segments",
        "value": round(rate, 1),
        "unit": "updates/sec",
        "vs_baseline": round(rate / 1_000_000.0, 3),
    }


def run_reconfig(seconds: float, smoke: bool) -> dict:
    """BASELINE ladder #5: joint-consensus reconfig cycles under churn
    (install joint views + collapse), batched over all ensembles."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    n_ens, m = (64, 5) if smoke else (10_000, 5)
    state = eng.init_state(n_ens, m, 8)
    up = jnp.ones((n_ens, m), bool)
    state, won = eng.elect_step(state, jnp.ones((n_ens,), bool),
                                jnp.zeros((n_ens,), jnp.int32), up)
    rng = np.random.default_rng(0)
    keep = np.ones((n_ens, m), bool)
    keep[np.arange(n_ens), rng.integers(0, m, n_ens)] = False
    shrink = jnp.asarray(keep)
    full = jnp.ones((n_ens, m), bool)
    yes = jnp.ones((n_ens,), bool)
    no = jnp.zeros((n_ens,), bool)

    def cycle(st):
        st, _, _ = eng.reconfig_step(st, yes, shrink, up)
        st, _, _ = eng.reconfig_step(st, no, shrink, up)
        st, _, _ = eng.reconfig_step(st, yes, full, up)
        st, _, _ = eng.reconfig_step(st, no, full, up)
        return st

    state = cycle(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        state = cycle(state)
        jax.block_until_ready(state)
    step_est = (time.perf_counter() - t0) / ncal
    iters = max(5, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = cycle(state)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0
    assert bool(np.asarray(won).all())
    # 2 full membership changes (4 reconfig phases) per cycle per ens
    rate = 2 * n_ens * iters / elapsed
    return {
        "metric": f"membership_changes_per_sec_{n_ens}_ens",
        "value": round(rate, 1),
        "unit": "changes/sec",
        "vs_baseline": round(rate / 1_000_000.0, 3),
    }


# -- §16 compartmentalized serving plane: the ingress rung -------------------

#: loadgen child source (run via ``python -c`` with one JSON argv):
#: an asyncio herd of simulated client connections importing ONLY the
#: wire codec — no jax — so thousands of connections cost a subprocess
#: fork, not an XLA init.  Each connection keeps one slab batch in
#: flight (closed-loop per connection, open-loop across the herd) and
#: the child prints ONE JSON tally line.
_INGRESS_LOADGEN = r'''
import asyncio, json, struct, sys, time

cfg = json.loads(sys.argv[1])
sys.path.insert(0, cfg["repo"])
try:  # the 10k-connection shape needs headroom past the soft FD cap
    import resource
    _h = resource.getrlimit(resource.RLIMIT_NOFILE)[1]
    if _h != resource.RLIM_INFINITY:
        resource.setrlimit(resource.RLIMIT_NOFILE, (_h, _h))
except Exception:
    pass
from riak_ensemble_tpu import wire

HDR = struct.Struct(">I")
addrs = [tuple(a) for a in cfg["addrs"]]
n_ens, k = cfg["n_ens"], cfg["k"]
mode = cfg["mode"]
write_every = cfg.get("write_every", 8)
stagger = cfg.get("stagger", 0.002)
ramp = cfg.get("ramp", 0.0)


def slab(keys):
    lens = struct.pack("<%di" % len(keys), *[len(s) for s in keys])
    return lens, "".join(keys).encode("ascii")


rlens, rarena = slab(["r%d" % j for j in range(k)])
wlens, warena = slab(["w%d" % j for j in range(k)])
vals = [b"v%03d" % j for j in range(k)]
vlens = struct.pack("<%di" % k, *[len(v) for v in vals])
varena = b"".join(vals)

tally = {"batches": 0, "read_ops": 0, "write_ops": 0, "rerouted": 0,
         "soft_errors": 0, "errors": 0}
lats = []
t0 = time.monotonic()
t_start = t0 + ramp
t_end = t_start + cfg["seconds"]


async def one(i):
    await asyncio.sleep(min(ramp, i * stagger))
    reader = writer = None
    for _ in range(200):  # the proxy tier may still be booting
        try:
            reader, writer = await asyncio.open_connection(
                *addrs[i % len(addrs)])
            break
        except OSError:
            await asyncio.sleep(0.05)
    if writer is None:
        tally["errors"] += 1
        return
    rid = 0
    try:
        while time.monotonic() < t_end:
            rid += 1
            wr = mode == "mixed" and rid % write_every == 0
            ens = (i + rid) % n_ens
            frame = ((rid, "kput_slab", ens, wlens, warena, vlens,
                      varena) if wr
                     else (rid, "kget_slab", ens, rlens, rarena))
            payload = wire.encode(frame)
            ts = time.monotonic()
            writer.write(HDR.pack(len(payload)) + payload)
            await writer.drain()
            head = await asyncio.wait_for(reader.readexactly(4), 60.0)
            (n,) = HDR.unpack(head)
            resp = wire.decode(await asyncio.wait_for(
                reader.readexactly(n), 60.0))
            te = time.monotonic()
            res = resp[1]
            if not isinstance(res, list):
                if res == ("error", "not-leader"):
                    tally["rerouted"] += 1  # replica lease lapsed
                    await asyncio.sleep(0.005)
                else:  # whole-batch soft failure (leader re-sync)
                    tally["soft_errors"] += 1
                    await asyncio.sleep(0.01)
                continue
            ok = sum(1 for r in res
                     if isinstance(r, tuple) and r and r[0] == "ok")
            tally["soft_errors"] += len(res) - ok
            if te < t_start:
                continue  # ramp: connections still piling on
            tally["batches"] += 1
            tally["write_ops" if wr else "read_ops"] += ok
            if len(lats) < 200000:
                lats.append(te - ts)
    except (asyncio.TimeoutError, asyncio.IncompleteReadError,
            ConnectionError, OSError):
        tally["errors"] += 1
    finally:
        if writer is not None:
            try:
                writer.close()
            except Exception:
                pass


async def herd():
    await asyncio.gather(*(one(i) for i in range(cfg["conns"])))


asyncio.run(herd())
tally["window"] = max(time.monotonic() - t_start, 1e-9)
lats.sort()


def pct(q):
    if not lats:
        return None
    return round(lats[min(len(lats) - 1, int(q * len(lats)))] * 1e3, 3)


tally["p50_ms"] = pct(0.50)
tally["p99_ms"] = pct(0.99)
print(json.dumps(tally), flush=True)
'''


def _ingress_ask(addr, *frame, timeout=60.0):
    """One svcnode-protocol round-trip on a fresh socket — the
    bench's sync control lane (prewrite, serving gates, the fleet
    scrape)."""
    import socket as _socket
    import struct as _struct

    from riak_ensemble_tpu import wire

    hdr = _struct.Struct(">I")
    with _socket.create_connection(addr, timeout=timeout) as s:
        s.settimeout(timeout)
        payload = wire.encode(frame)
        s.sendall(hdr.pack(len(payload)) + payload)
        buf = b""
        while len(buf) < 4:
            b = s.recv(4 - len(buf))
            if not b:
                raise ConnectionError("closed")
            buf += b
        (n,) = hdr.unpack(buf)
        buf = b""
        while len(buf) < n:
            b = s.recv(min(1 << 16, n - len(buf)))
            if not b:
                raise ConnectionError("closed")
            buf += b
        return wire.decode(buf)[1]


def _ingress_control(port, frame, timeout=180.0):
    """Raw repl-port control round-trip (``("promote", peers)``)."""
    import socket as _socket

    from riak_ensemble_tpu.parallel import repgroup

    with _socket.create_connection(("127.0.0.1", port),
                                   timeout=timeout) as s:
        s.settimeout(timeout)
        repgroup.send_frame(s, frame)
        return repgroup.recv_frame(s)


def _ingress_prewrite(leader, n_ens, k, budget=240.0):
    """Seed every ensemble's read keys through the fresh leader —
    doubling as the serving gate (the first writes retry through the
    post-promote host-quorum heal)."""
    import struct as _struct

    keys = ["r%d" % j for j in range(k)]
    lens = _struct.pack("<%di" % k, *[len(s) for s in keys])
    arena = "".join(keys).encode("ascii")
    vals = [b"v%03d" % j for j in range(k)]
    vlens = _struct.pack("<%di" % k, *[len(v) for v in vals])
    varena = b"".join(vals)
    deadline = time.monotonic() + budget
    for e in range(n_ens):
        while True:
            try:
                rs = _ingress_ask(leader, 1, "kput_slab", e, lens,
                                  arena, vlens, varena)
            except (ConnectionError, OSError):
                rs = None
            if isinstance(rs, list) and all(
                    isinstance(r, tuple) and r and r[0] == "ok"
                    for r in rs):
                break
            assert time.monotonic() < deadline, \
                f"ingress prewrite never converged: {rs!r}"
            time.sleep(0.25)


def _ingress_spawn_host(n_ens, n_slots, tmp, i, procs):
    """One group host OS process for the full-shape arm (its own
    GIL — ingress scaling is invisible when every tier shares one
    interpreter), follower reads on, the rung's lease/heartbeat
    config.  The ready line carries both ports; the child lands in
    ``procs`` before the parse so it can never leak past the
    caller's kill sweep."""
    import textwrap
    import threading

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = textwrap.dedent(f"""
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {repo!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          {repo!r} + "/.jax_cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
        from riak_ensemble_tpu.config import Config
        from riak_ensemble_tpu.parallel import repgroup
        srv = repgroup.ReplicaServer(
            {n_ens}, 3, {n_slots}, data_dir={tmp!r} + "/r{i}",
            config=Config(ensemble_tick=0.05, lease_duration=1.5,
                          probe_delay=0.1, storage_delay=0.005,
                          storage_tick=0.5, gossip_tick=0.2),
            follower_reads=True)
        print("ready repl=%d client=%d"
              % (srv.repl_port, srv.client_port), flush=True)
        while True:
            time.sleep(60)
    """)
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True, env=env)
    procs.append(p)
    line = p.stdout.readline()
    assert line.startswith("ready"), f"ingress host died: {line!r}"
    parts = dict(kv.split("=") for kv in line.split()[1:])
    threading.Thread(target=lambda f=p.stdout: [None for _ in f],
                     daemon=True).start()
    return int(parts["repl"]), int(parts["client"])


def _ingress_spawn_proxies(count, hosts, procs):
    """``count`` proxy OS processes fronting the same group; returns
    (children, client-facing addrs).  Spawned concurrently — each
    pays a jax import — ready lines parsed after."""
    import threading

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    up = ",".join(f"{h}:{p}" for h, p in hosts)
    px = []
    for _ in range(count):
        p = subprocess.Popen(
            [sys.executable, "-m", "riak_ensemble_tpu.proxy",
             "--port", "0", "--upstream", up,
             "--discover-timeout", "120"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env)
        procs.append(p)
        px.append(p)
    addrs = []
    for p in px:
        line = p.stdout.readline()
        assert line.startswith("proxy serving on "), \
            f"ingress proxy died: {line!r}"
        host, _, port = line.split()[3].rpartition(":")
        addrs.append((host, int(port)))
        threading.Thread(target=lambda f=p.stdout: [None for _ in f],
                         daemon=True).start()
    return px, addrs


def _ingress_loadgens(cfgs, procs, budget):
    """Run the loadgen herd children to completion; one parsed tally
    per child."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    kids = []
    for c in cfgs:
        p = subprocess.Popen(
            [sys.executable, "-c", _INGRESS_LOADGEN, json.dumps(c)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)
        procs.append(p)
        kids.append(p)
    out = []
    for p in kids:
        stdout, stderr = p.communicate(timeout=budget)
        assert p.returncode == 0, \
            f"ingress loadgen died: {stderr[-400:]}"
        out.append(json.loads(stdout.strip().splitlines()[-1]))
    return out


def _ingress_tally(results):
    """Fold per-child tallies into one arm record: counts sum, the
    window is the slowest child's (rates stay conservative), the
    latency columns are the worst any child observed."""
    window = max(r["window"] for r in results)
    agg = {key: sum(r[key] for r in results)
           for key in ("batches", "read_ops", "write_ops",
                       "rerouted", "soft_errors", "errors")}
    p50 = [r["p50_ms"] for r in results if r["p50_ms"] is not None]
    p99 = [r["p99_ms"] for r in results if r["p99_ms"] is not None]
    return {
        "batches_per_sec": round(agg["batches"] / window, 1),
        "read_ops_per_sec": round(agg["read_ops"] / window, 1),
        "write_ops_per_sec": round(agg["write_ops"] / window, 1),
        "client_p50_ms": max(p50) if p50 else None,
        "client_p99_ms": max(p99) if p99 else None,
        "rerouted": agg["rerouted"],
        "soft_errors": agg["soft_errors"],
        "errors": agg["errors"],
    }


def _ingress_engine_p99(fm):
    """Worst engine-tier ``retpu_op_latency_ms`` p99 across the fleet
    snapshot (the PR 8 op rings; base series plus labeled tenants)."""
    best = None
    hosts = fm.get("hosts") if isinstance(fm, dict) else None
    for snap in (hosts or {}).values():
        h = snap.get("retpu_op_latency_ms") \
            if isinstance(snap, dict) else None
        if not isinstance(h, dict):
            continue
        for hh in [h] + list((h.get("by_label") or {}).values()):
            v = hh.get("p99") if isinstance(hh, dict) else None
            if isinstance(v, (int, float)) and v == v \
                    and (best is None or v > best):
                best = float(v)
    return best


def _ingress_follower_served(fm):
    """Every host's ``retpu_group_follower_reads_served`` summed out
    of the fleet snapshot — the replicas' own proof the spread arm
    was served from mirrors, riding the same single pull."""
    total = 0
    hosts = fm.get("hosts") if isinstance(fm, dict) else None
    for snap in (hosts or {}).values():
        v = snap.get("retpu_group_follower_reads_served") \
            if isinstance(snap, dict) else None
        if isinstance(v, dict):
            v = sum(x for x in v.values()
                    if isinstance(x, (int, float)))
        if isinstance(v, (int, float)):
            total += int(v)
    return total


def run_ingress(seconds: float, smoke: bool) -> dict:
    """§16 serving-plane rung: proxy-count ingress scaling and the
    follower-read A/B against ONE promoted 3-host replication group.

    Two interleaved A/Bs ride the round JSON:

    - **ingress scaling** — an open-loop herd of simulated client
      connections drives mixed slab batches through 1 vs N stateless
      proxies (each its own OS process, svcnode wire protocol, one
      scatter-gather hop per batch); acceptance wants the
      client-batch ingestion rate to scale >= 1.5x from 1 -> 4
      proxies at the round shape while write throughput (quorum-
      bound at the leader — proxies can't help it) holds within 10%.
    - **follower reads** — the same read workload aimed at the
      leader alone vs spread over all three hosts with replica-
      served leased reads answering from delta-maintained mirrors;
      acceptance wants >= 1.8x read throughput on the 3-host group.

    Per-tier evidence: client-observed p50/p99 from the herd (the
    ingress tier) and the engine-tier ``retpu_op_latency_ms`` p99
    from the PR 8 op rings — every host's registry scraped in ONE
    ``("fleet", "metrics")`` pull off the leader (§11), which also
    carries the replicas' follower-read counters.

    The smoke shape keeps the GROUP in process (threaded hosts,
    shared jit cache — the tier-1 budget) with proxies and loadgens
    as real subprocesses; its ratios are structural sanity, not a
    measure (every smoke host shares one GIL).  The full shape runs
    3 host processes, (1, 4) proxy processes and an 8-child herd
    sized 10k+ connections (capped to the box's FD budget)."""
    import shutil
    import statistics
    import tempfile

    if smoke:
        n_ens, n_slots, k = 8, 16, 4
        proxy_counts, reps, gens, gens_flw = (1, 2), 1, 2, 1
        conns, flw_conns = 16, 9
        measure = max(0.5, min(seconds, 1.0))
    else:
        n_ens, n_slots, k = 32, 32, 8
        proxy_counts, reps, gens, gens_flw = (1, 4), 2, 8, 2
        try:
            import resource
            hard = resource.getrlimit(resource.RLIMIT_NOFILE)[1]
            cap = 10_000 if hard == resource.RLIM_INFINITY \
                else max(512, (hard - 512) // 2)
        except Exception:
            cap = 10_000
        conns, flw_conns = min(10_000, cap), 48
        measure = max(5.0, seconds)

    tmp = tempfile.mkdtemp(prefix="bench_ingress_")
    procs: list = []
    srvs: list = []
    try:
        # -- one 3-host group, host 0 promoted -------------------------
        if smoke:
            from riak_ensemble_tpu.config import Config
            from riak_ensemble_tpu.parallel import repgroup
            cfg = Config(ensemble_tick=0.05, lease_duration=1.5,
                         probe_delay=0.1, storage_delay=0.005,
                         storage_tick=0.5, gossip_tick=0.2)
            srvs = [repgroup.ReplicaServer(
                n_ens, 3, n_slots, data_dir=f"{tmp}/r{i}",
                config=cfg, follower_reads=True) for i in range(3)]
            ports = [(s.repl_port, s.client_port) for s in srvs]
        else:
            ports = [_ingress_spawn_host(n_ens, n_slots, tmp, i,
                                         procs) for i in range(3)]
        repl_ports = [r for r, _c in ports]
        hosts = [("127.0.0.1", c) for _r, c in ports]
        leader = hosts[0]
        resp = _ingress_control(
            repl_ports[0],
            ("promote", [("127.0.0.1", p) for p in repl_ports[1:]]))
        assert resp[0] == "ok", f"ingress promote failed: {resp!r}"
        _ingress_prewrite(leader, n_ens, k)

        repo = os.path.dirname(os.path.abspath(__file__))

        def herd_cfg(addrs, n, mode, ramp):
            return dict(repo=repo, addrs=[list(a) for a in addrs],
                        conns=n, seconds=measure, mode=mode,
                        write_every=8, n_ens=n_ens, k=k, ramp=ramp,
                        stagger=0.002)

        # -- A/B 1: ingress scaling, arm order mirrored per rep --------
        order = []
        for r in range(reps):
            order += list(proxy_counts if r % 2 == 0
                          else tuple(reversed(proxy_counts)))
        arm_recs = {p: [] for p in proxy_counts}
        for count in order:
            px, paddrs = _ingress_spawn_proxies(count, hosts, procs)
            per = max(1, conns // gens)
            ramp = min(2.0, per * 0.002)
            res = _ingress_loadgens(
                [herd_cfg(paddrs, per, "mixed", ramp)
                 for _ in range(gens)],
                procs, budget=measure + ramp + 180.0)
            arm = _ingress_tally(res)
            arm["conns"] = per * gens
            arm_recs[count].append(arm)
            for p in px:
                p.kill()

        arms = {}
        for count in proxy_counts:
            a = dict(arm_recs[count][-1])
            for key in ("batches_per_sec", "read_ops_per_sec",
                        "write_ops_per_sec"):
                a[key] = statistics.median(
                    rec[key] for rec in arm_recs[count])
            arms[str(count)] = a
        lo, hi = str(min(proxy_counts)), str(max(proxy_counts))
        ingress_x = round(arms[hi]["batches_per_sec"]
                          / max(arms[lo]["batches_per_sec"], 1e-9), 3)
        w_lo = arms[lo]["write_ops_per_sec"]
        write_hold = round(arms[hi]["write_ops_per_sec"] / w_lo, 3) \
            if w_lo > 0 else None

        # -- A/B 2: follower-served reads, arm order mirrored ----------
        # gate: both replicas must hold a live lease before the
        # spread arm measures (grants rode the prewrite settles; the
        # idle leader's heartbeats renew them)
        deadline = time.monotonic() + 60.0
        for addr in hosts[1:]:
            while _ingress_ask(addr, 0, "kget", 0, "r0") == \
                    ("error", "not-leader"):
                assert time.monotonic() < deadline, \
                    "follower lease never arrived"
                time.sleep(0.25)
        flw_recs = {"leader_only": [], "followers": []}
        flw_order = []
        for r in range(reps):
            pair = ["leader_only", "followers"]
            flw_order += pair if r % 2 == 0 else pair[::-1]
        for name in flw_order:
            addrs = [leader] if name == "leader_only" else hosts
            per = max(1, flw_conns // gens_flw)
            res = _ingress_loadgens(
                [herd_cfg(addrs, per, "read", 0.1)
                 for _ in range(gens_flw)],
                procs, budget=measure + 180.0)
            flw_recs[name].append(_ingress_tally(res))
        flw = {}
        for name, recs in flw_recs.items():
            rec = dict(recs[-1])
            rec["read_ops_per_sec"] = statistics.median(
                r["read_ops_per_sec"] for r in recs)
            rec["conns"] = max(1, flw_conns // gens_flw) * gens_flw
            flw[name] = rec
        follower_x = round(
            flw["followers"]["read_ops_per_sec"]
            / max(flw["leader_only"]["read_ops_per_sec"], 1e-9), 3)

        # -- per-tier evidence: ONE fleet pull off the leader ----------
        fm = _ingress_ask(leader, 1, "fleet", "metrics", timeout=120.0)
        return {
            "ingress_x": ingress_x,
            "ingress_write_hold": write_hold,
            "ingress_arms": arms,
            "ingress_conns": conns,
            "ingress_engine_p99_ms": _ingress_engine_p99(fm),
            "follower_read_x": follower_x,
            "follower_read_arms": flw,
            "follower_reads_served_total": _ingress_follower_served(fm),
            "ingress_shape": {
                "n_ens": n_ens, "n_slots": n_slots, "k": k,
                "proxies": list(proxy_counts), "reps": reps,
                "measure_s": measure, "smoke": smoke},
        }
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        for s in srvs:
            try:
                s.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _commrepl_arm(seconds: float, smoke: bool, n_ens: int,
                  n_slots: int, n_keys: int, dup: int,
                  comm: bool) -> dict:
    """One arm of the commrepl A/B: a 3-host group driven by a
    contended-counter kmodify_many storm (every hot key duplicated
    ``dup`` times per batch).  ``comm`` flips the leader's
    ``RETPU_COMM_REPL`` lane — replicas apply whichever entry kind
    arrives, so only the leader's flag differs between arms."""
    import shutil
    import signal
    import tempfile

    from riak_ensemble_tpu import funref
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel import repgroup
    from riak_ensemble_tpu.parallel.batched_host import WallRuntime

    tmp = tempfile.mkdtemp(prefix="bench_commrepl_")
    procs: list = []
    servers: list = []
    try:
        ports = []
        if smoke:
            for i in (1, 2):
                servers.append(repgroup.ReplicaServer(
                    n_ens, 3, n_slots, data_dir=f"{tmp}/r{i}",
                    config=fast_test_config()))
            ports = [s.repl_port for s in servers]
        else:
            for i in (1, 2):
                ports.append(_repgroup_spawn_subprocess(
                    n_ens, n_slots, tmp, i, procs))
        svc = repgroup.ReplicatedService(
            WallRuntime(), n_ens, 1, n_slots, group_size=3,
            peers=[("127.0.0.1", p) for p in ports],
            ack_timeout=60.0, max_ops_per_tick=n_keys * dup,
            config=fast_test_config(), data_dir=tmp + "/leader",
            pipeline_depth=2)
        svc._comm_repl = comm  # the A/B flip (RETPU_COMM_REPL)
        repgroup.warmup_kernels(svc)
        assert svc.takeover(), "commrepl bench: takeover failed"

        fun = funref.ref("rmw:add", 1)
        storm = [f"ctr{j}" for j in range(n_keys)] * dup

        futs = [svc.kmodify_many(e, storm, fun)
                for e in range(n_ens)]
        while any(svc.queues):  # warm: slots, elections, compile
            svc.flush()
        assert all(f.done for f in futs)
        svc.ack_timeout = 10.0
        g0 = dict(svc.stats()["group"])

        lat = []
        ops = 0
        inflight = []
        t_end = time.perf_counter() + max(seconds, 1e-3)
        t0 = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now < t_end and len(inflight) < 4:
                inflight.append((now, [
                    svc.kmodify_many(e, storm, fun)
                    for e in range(n_ens)]))
            svc.flush()
            while inflight and all(f.done for f in inflight[0][1]):
                tb, fl = inflight.pop(0)
                lat.append(time.perf_counter() - tb)
                ops += len(fl) * len(storm)
            if now >= t_end and not inflight and lat:
                break
            assert now < t_end + 120.0, "commrepl bench wedged"
        elapsed = time.perf_counter() - t0
        g = svc.stats()["group"]
        assert g["quorum_failures"] == 0, g
        entries = max((g["repl_delta_entries"] + g["repl_full_entries"])
                      - (g0["repl_delta_entries"]
                         + g0["repl_full_entries"]), 1)
        out = {
            "ops_per_sec": round(ops / elapsed, 1),
            "ack_p50_ms": round(float(np.percentile(
                np.asarray(lat) * 1e3, 50)), 3),
            "ack_p99_ms": round(float(np.percentile(
                np.asarray(lat) * 1e3, 99)), 3),
            "bytes_per_entry": round(
                (g["repl_bytes_sections"] - g0["repl_bytes_sections"])
                / entries, 1),
            "merge_entries": (g["repl_merge_entries"]
                              - g0["repl_merge_entries"]),
            "merge_cells": (g["repl_merge_cells"]
                            - g0["repl_merge_cells"]),
            "early_acks": (g["repl_early_acks"]
                           - g0["repl_early_acks"]),
            "coalesce_ratio": g["repl_merge_coalesce_ratio"],
        }
        if smoke:
            # comm/ordered convergence tripwire: every replica lane's
            # engine state bit-equal to the leader's after drain
            for _ in range(3):
                svc.heartbeat()
            svc._drain_pending(block_all=True)
            want_pos = (svc.core.applied_ge, svc.core.applied_seq)
            end = time.monotonic() + 60.0
            while time.monotonic() < end:
                done = True
                for s in servers:
                    with s._lock:
                        done = done and ((s.core.applied_ge,
                                          s.core.applied_seq)
                                         >= want_pos)
                if done:
                    break
                time.sleep(0.02)
            d_l = repgroup.dump_state(svc)
            ok = True
            for s in servers:
                with s._lock:
                    d_r = repgroup.dump_state(s.svc)
                ok = ok and d_l[0] == d_r[0]
            out["convergence_ok"] = ok
        svc.stop()
        return out
    finally:
        for s in servers:
            s.stop()
        for p in procs:
            try:
                p.send_signal(signal.SIGKILL)
            except ProcessLookupError:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def run_commrepl(seconds: float, smoke: bool) -> dict:
    """Commutative-replication rung (ARCHITECTURE §18): the contended-
    counter storm — hot keys duplicated per batch, rmw:add only — on a
    3-host group, comm lane vs ordered A/B.  The comm arm coalesces
    duplicates at enqueue, ships merge sections and early-acks on
    merge-durable quorum receipt; the ordered arm (``svc._comm_repl =
    False``, the ``RETPU_COMM_REPL=0`` semantics) pays full per-entry
    sequencing.  ``rmw_comm_x`` = ordered ack p50 / comm ack p50
    (higher is better; ``tools/bench_trend.py --check`` rides it), and
    the bytes-per-entry pair feeds the test_bench_smoke tripwire
    (merge section < ordered delta bytes on the hot-slot shape)."""
    n_ens, n_slots, n_keys, dup = ((8, 16, 2, 4) if smoke
                                   else (32, 32, 4, 8))
    comm = _commrepl_arm(seconds, smoke, n_ens, n_slots, n_keys,
                         dup, True)
    plain = _commrepl_arm(seconds, smoke, n_ens, n_slots, n_keys,
                          dup, False)
    out = {
        "commrepl_ops_per_sec": comm["ops_per_sec"],
        "commrepl_ack_p50_ms": comm["ack_p50_ms"],
        "commrepl_ack_p99_ms": comm["ack_p99_ms"],
        "commrepl_ordered_ack_p50_ms": plain["ack_p50_ms"],
        "commrepl_ordered_ack_p99_ms": plain["ack_p99_ms"],
        "commrepl_bytes_per_entry": comm["bytes_per_entry"],
        "commrepl_ordered_bytes_per_entry": plain["bytes_per_entry"],
        "commrepl_merge_entries": comm["merge_entries"],
        "commrepl_merge_cells": comm["merge_cells"],
        "commrepl_early_acks": comm["early_acks"],
        "commrepl_coalesce_ratio": comm["coalesce_ratio"],
        "commrepl_shape": {
            "n_ens": n_ens, "n_slots": n_slots, "n_keys": n_keys,
            "dup": dup, "smoke": smoke},
        "rmw_comm_x": round(
            plain["ack_p50_ms"] / max(comm["ack_p50_ms"], 1e-9), 3),
    }
    if "convergence_ok" in comm:
        out["commrepl_convergence_ok"] = (comm["convergence_ok"]
                                          and plain["convergence_ok"])
    return out


#: fallback ladder: (label, shapes, per-stage subprocess timeout).
#: Full TPU shapes first; smaller shapes if the backend is too slow to
#: compile/run the big ones; forced-CPU small shapes as the last
#: resort so SOME honest number always lands.
_ATTEMPTS = (
    ("10k_ens_5_peers",
     dict(n_ens=10_000, n_peers=5, n_slots=128, k=64), 420.0, False),
    ("1k_ens_5_peers",
     dict(n_ens=1_000, n_peers=5, n_slots=128, k=32), 300.0, False),
    # The CPU rung is sized so one service batch takes ~0.3s, not
    # ~1.4s: with the default 3s budget that yields ~10 latency
    # samples (a 1-batch run makes p50/p99 degenerate).
    ("512_ens_5_peers_cpu",
     dict(n_ens=512, n_peers=5, n_slots=64, k=16), 300.0, True),
)


def _spawn_stage(cmd, timeout: float, env=None):
    """One killable worker subprocess: own session (the whole process
    GROUP is killed on timeout — a wedged tunnel helper holding the
    inherited stdout pipe would otherwise block the drain forever),
    last-JSON-line result parse.  Returns (parsed, error_string)."""
    import signal

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            pass
        return None, f"timeout after {timeout}s"
    if proc.returncode != 0:
        return None, f"rc={proc.returncode} {err[-400:]}"
    for line in reversed(out.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no json line"


def _run_stage(stage: str, label: str, shapes: dict, seconds: float,
               timeout: float, force_cpu: bool, env=None):
    """Run one stage in a subprocess; parse its JSON line; None on
    timeout/crash (a wedged TPU RPC ignores signals — only a
    subprocess kill reliably unsticks the bench).

    The budget scales with the requested measurement time (the
    constant part covers compile + warmup + transfers).  ``env``
    (full environment dict) lets mesh stages inject XLA_FLAGS —
    device-count flags bind at jax import, so they can only enter a
    stage through its subprocess environment.
    """
    timeout = timeout + max(0.0, (seconds - 3.0) * 4.0)
    cmd = [sys.executable, os.path.abspath(__file__), "--stage", stage,
           "--seconds", str(seconds)]
    for f, v in shapes.items():
        cmd += [f"--{f.replace('_', '-')}", str(v)]
    if force_cpu:
        cmd.append("--force-cpu")
    result, err = _spawn_stage(cmd, timeout, env=env)
    if err is not None:
        print(f"# stage {stage}@{label}: {err}", file=sys.stderr)
    return result


def _mesh_cpu_env(n_devices: int = 8) -> dict:
    """Stage environment with the virtual CPU device count forced (a
    no-op on a real accelerator platform — the flag only affects the
    host CPU client).  Merged with any existing XLA_FLAGS."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env


def _stage_entry(args) -> None:
    """Worker mode: one stage, one process, one JSON line on stdout."""
    _setup_jax(args.force_cpu)
    if args.stage == "probe":
        # Accelerator preflight: one tiny compiled op.  A dead/wedged
        # tunnel hangs here (and only costs the probe's short budget)
        # instead of burning every full-shape attempt's timeout.
        import jax
        import jax.numpy as jnp
        x = jnp.ones((8, 128)) @ jnp.ones((128, 8))
        jax.block_until_ready(x)
        print(json.dumps({"platform": jax.devices()[0].platform}))
        return
    shapes = dict(n_ens=args.n_ens, n_peers=args.n_peers,
                  n_slots=args.n_slots, k=args.k)
    if args.stage == "kernel":
        out = {"kernel_rounds_per_sec": run(seconds=args.seconds, **shapes)}
    elif args.stage == "escale":
        out = {"escale": run_escale_point(
            seconds=args.seconds, mesh_devices=args.mesh_devices,
            **shapes)}
    elif args.stage == "tpuprobe":
        out = run_tpuprobe(args.seconds)
    elif args.stage == "stepprobe":
        out = run_stepprobe(**shapes)
    elif args.stage == "widecmp":
        out = run_widecmp(seconds=args.seconds, **shapes)
    elif args.stage == "repgroup":
        out = run_repgroup(args.seconds, smoke=False)
    elif args.stage == "faultsweep":
        out = run_faultsweep(args.seconds, smoke=False)
    elif args.stage == "autotune":
        out = run_autotune(args.seconds, smoke=False)
    elif args.stage == "fleetobs":
        out = run_fleet_obs_overhead(args.seconds)
    elif args.stage == "recovery":
        out = run_recovery(args.seconds, smoke=False)
    elif args.stage == "ingress":
        out = run_ingress(args.seconds, smoke=False)
    elif args.stage == "commrepl":
        out = run_commrepl(args.seconds, smoke=False)
    elif args.stage == "merkle":
        m = run_merkle(args.seconds, smoke=False)
        out = {"ladder_metric": m["metric"], "ladder_value": m["value"]}
    elif args.stage == "reconfig":
        m = run_reconfig(args.seconds, smoke=False)
        out = {"ladder_metric": m["metric"], "ladder_value": m["value"]}
    else:
        out = run_service(seconds=args.seconds, **shapes)
    import jax
    out["platform"] = jax.devices()[0].platform
    # every stage's JSON carries the box fingerprint (cpu count,
    # loadavg, jax versions, RETPU_* knobs) — cross-round comparisons
    # check the box before believing a delta (the r4→r5 lesson).
    # device_count joins it here (after jax init — the fingerprint
    # helper itself must never initialize a backend): escale points
    # from different mesh widths must never ratchet against each
    # other.
    from riak_ensemble_tpu.obs import box_fingerprint
    out["box"] = box_fingerprint()
    out["box"]["device_count"] = jax.device_count()
    print(json.dumps(out))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a CPU sanity run")
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--scenario", default="kv",
                    choices=("kv", "merkle", "reconfig"),
                    help="kv = headline (driver default); merkle / "
                         "reconfig = BASELINE.md ladder #4 / #5")
    ap.add_argument("--stage",
                    choices=("kernel", "service", "merkle", "reconfig",
                             "probe", "stepprobe", "repgroup",
                             "widecmp", "escale", "faultsweep",
                             "autotune", "fleetobs", "recovery",
                             "ingress", "commrepl", "tpuprobe"),
                    help="internal: run one stage in-process")
    ap.add_argument("--mesh-devices", type=int, default=0,
                    help="escale stage: shard the engine over this "
                         "many devices along the 'ens' axis (0 = "
                         "single-shard; CPU needs XLA_FLAGS="
                         "--xla_force_host_platform_device_count "
                         "in the stage environment)")
    ap.add_argument("--n-ens", type=int, default=10_000)
    ap.add_argument("--n-peers", type=int, default=5)
    ap.add_argument("--n-slots", type=int, default=128)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args()

    if args.stage:
        _stage_entry(args)
        return
    if args.scenario == "merkle":
        _setup_jax(False)
        print(json.dumps(run_merkle(args.seconds, args.smoke)))
        return
    if args.scenario == "reconfig":
        _setup_jax(False)
        print(json.dumps(run_reconfig(args.seconds, args.smoke)))
        return

    if args.smoke:
        _setup_jax(force_cpu=True)  # smoke = sanity check, not a measure
        # bench-trend ratchet rides the smoke path: a malformed or
        # headline-less BENCH round fails the smoke run LOUDLY (the
        # TrendError propagates) instead of shipping an unreadable
        # trajectory into the next round
        from tools import bench_trend
        trend = bench_trend.check(
            os.path.dirname(os.path.abspath(__file__)))
        shapes = dict(n_ens=64, n_peers=5, n_slots=32, k=4)
        secs = min(args.seconds, 1.0)
        kernel_rounds = run(seconds=secs, **shapes)
        svc = run_service(seconds=secs, **shapes)
        svc["kernel_rounds_per_sec"] = kernel_rounds
        svc.update(run_repgroup(secs, smoke=True))
        svc.update(run_faultsweep(secs, smoke=True))
        svc.update(run_autotune(secs, smoke=True))
        svc.update(run_fleet_obs_overhead(secs))
        svc.update(run_recovery(secs, smoke=True))
        svc.update(run_ingress(secs, smoke=True))
        svc.update(run_commrepl(secs, smoke=True))
        svc["platform"] = "smoke"
        svc["bench_trend"] = trend
        label = "64_ens_5_peers_smoke"
    else:
        # Within a label the kernel stage runs FIRST: a d2h transfer
        # degrades subsequent dispatch on the tunneled chip (measured
        # 40x) and that state has outlived processes before, so the
        # service stage (d2h every batch) must not precede the kernel
        # measurement.  Both stages get the fallback ladder — the
        # first label where the service (the headline) succeeds wins,
        # and the kernel keeps falling back independently if its
        # attempt at that label failed.
        # Preflight: if a tiny compiled op can't finish in 150s, the
        # accelerator/tunnel is down — skip straight to the CPU rungs
        # rather than burning every full-shape attempt's budget.
        attempts = _ATTEMPTS
        # 240s: ~10x the observed healthy cold-init+compile time (~26s
        # through the tunnel), so only a genuinely dead backend trips it.
        probe = _run_stage("probe", "preflight", {}, 0.0, 240.0, False)
        if probe is None or probe.get("platform") == "cpu":
            # Dead tunnel — or JAX silently fell back to CPU (no
            # accelerator plugin): either way the full-shape
            # accelerator rungs would just burn their budgets.
            print("# accelerator preflight: "
                  + ("failed" if probe is None else "cpu fallback")
                  + "; CPU rungs only", file=sys.stderr)
            attempts = tuple(a for a in _ATTEMPTS if a[3])
        svc = kern = None
        kern_label = None
        for label, shapes, budget, force_cpu in attempts:
            if kern is None:
                kern = _run_stage("kernel", label, shapes, args.seconds,
                                  budget, force_cpu)
                if kern is not None:
                    kern_label = label
            svc = _run_stage("service", label, shapes, args.seconds,
                             budget, force_cpu)
            if svc is not None:
                break
        if svc is not None and kern is None:
            # The headline landed but the kernel attempt at (or
            # before) that label wedged: keep walking the remaining
            # smaller/CPU rungs for the kernel number alone.
            start = next(i for i, a in enumerate(attempts)
                         if a[0] == label)
            for label2, shapes2, budget2, force_cpu2 in \
                    attempts[start + 1:]:
                kern = _run_stage("kernel", label2, shapes2,
                                  args.seconds, budget2, force_cpu2)
                if kern is not None:
                    kern_label = label2
                    break
        if svc is not None:
            svc["kernel_rounds_per_sec"] = (
                kern["kernel_rounds_per_sec"] if kern else None)
            svc["kernel_label"] = kern_label
            # BASELINE ladder #4 (1M-segment incremental Merkle
            # updates) on whatever platform the headline landed on.
            # BASELINE ladder #4 (Merkle) and #5 (reconfig churn),
            # keyed by the runner's OWN metric string so the reported
            # shape can never drift from the measured one.
            svc["ladder"] = {}
            for stage in ("merkle", "reconfig"):
                r = _run_stage(stage, label, {}, args.seconds,
                               300.0, force_cpu)
                if r is not None:
                    svc["ladder"][r["ladder_metric"]] = r["ladder_value"]
            # cross-host replication-group rung (3 OS processes,
            # fsync WALs, host-majority barrier) — CPU-bound sockets
            # + disk, so it runs whatever platform the headline took
            r = _run_stage("repgroup", label, {}, args.seconds,
                           420.0, force_cpu)
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith(("repgroup_", "repl_"))})
            # adversarial fault-injection rungs (ARCHITECTURE §13):
            # RTT sweep (depth 1 vs 2 under a slow link), fsync-delay
            # rung, noisy-tenant isolation — sockets + disk + CPU, so
            # it rides whatever platform the headline took.  The
            # 8-device env arms the stage's mesh rung (the same A/B
            # with the leader's lane sharded along 'ens').
            r = _run_stage("faultsweep", label, {}, args.seconds,
                           700.0, force_cpu, env=_mesh_cpu_env(8))
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith("faultsweep")})
            # autotune A/B (ARCHITECTURE §14): the controller arm vs
            # the best static (depth, window) at 0/5 ms injected ack
            # RTT, plus the tenant-guard rung — same socket/disk
            # profile as the faultsweep, same platform rule (8-device
            # env arms its mesh point)
            r = _run_stage("autotune", label, {}, args.seconds,
                           700.0, force_cpu, env=_mesh_cpu_env(8))
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith("autotune")})
            # fleet-federation overhead A/B (ARCHITECTURE §11): the
            # standing watchdog pull on vs off over an in-process
            # 3-host group — bound < 2%, the PR 8 op-trace bar
            r = _run_stage("fleetobs", label, {}, args.seconds,
                           420.0, force_cpu)
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith("fleet_obs")})
            # restart-to-serving rung (ARCHITECTURE §15): checkpoint
            # restore + WAL replay + first-op warmup at the 512-ens
            # shape — disk + host + compile, so it rides whatever
            # platform the headline took
            r = _run_stage("recovery", label, {}, args.seconds,
                           420.0, force_cpu)
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith("recovery_")})
            # §16 serving-plane rung: proxy-count ingress scaling +
            # the follower-read A/B over a real 3-process group with
            # subprocess proxies and a 10k-connection client herd —
            # sockets + GIL-bound parsing, so it rides whatever
            # platform the headline took
            r = _run_stage("ingress", label, {}, args.seconds,
                           600.0, force_cpu)
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith(("ingress_",
                                             "follower_"))})
            # §18 commutative-replication rung: contended-counter
            # storm, comm vs ordered A/B over a real 3-process group
            # — sockets + disk + host resolve, so it rides whatever
            # platform the headline took
            r = _run_stage("commrepl", label, {}, args.seconds,
                           600.0, force_cpu)
            if r is not None:
                svc.update({k: v for k, v in r.items()
                            if k.startswith(("commrepl_",
                                             "rmw_comm_x"))})
            # E-scaling datapoints (ROADMAP carried debt item 2): the
            # 1k-ens CPU rung always rides the round JSON; the 2k-
            # and 4k-ens points land when the box completes them
            # inside their own budgets (each point is its own
            # killable stage, so a slow deep attempt can never cost
            # the shallower numbers)
            svc["escale_cpu"] = {}
            for ee in (1024, 2048, 4096):
                r = _run_stage("escale", f"{ee}_ens_cpu",
                               dict(n_ens=ee, n_peers=5, n_slots=64,
                                    k=16), args.seconds, 360.0, True)
                if r is None:
                    break
                svc["escale_cpu"][str(ee)] = r["escale"]
            # Mesh E-scaling ladder (ROADMAP open item 2): the fused
            # step sharded over 8 virtual CPU devices along 'ens',
            # 10k and 32k required rungs plus a best-effort 100k.
            # Each mesh point pairs with a SINGLE-SHARD reference at
            # E/8 — equal per-shard load — and scaling efficiency is
            # mesh ops/s over 8x the reference: honest numbers,
            # whatever they are, with device count in each stage's
            # box fingerprint.  Both arms run in the same 8-device
            # environment so their fingerprints match.
            env8 = _mesh_cpu_env(8)
            svc["escale_mesh"] = {}
            for ee in (10_240, 32_768, 102_400):
                r = _run_stage("escale", f"{ee}_ens_mesh8",
                               dict(n_ens=ee, n_peers=5, n_slots=64,
                                    k=16, mesh_devices=8),
                               args.seconds, 600.0, True, env=env8)
                if r is None:
                    break
                point = r["escale"]
                ref = _run_stage("escale", f"{ee // 8}_ens_ref",
                                 dict(n_ens=ee // 8, n_peers=5,
                                      n_slots=64, k=16),
                                 args.seconds, 360.0, True, env=env8)
                if ref is not None:
                    ref_ops = ref["escale"]["ops_per_sec"]
                    point["single_ref_n_ens"] = ee // 8
                    point["single_ref_ops_per_sec"] = ref_ops
                    point["escale_eff"] = (
                        round(point["ops_per_sec"] / (8 * ref_ops), 3)
                        if ref_ops else None)
                svc["escale_mesh"][str(ee)] = point
            # headline efficiency for the trend ratchet: the >=10k
            # acceptance rung (device count rides the fingerprint)
            p10k = svc["escale_mesh"].get("10240")
            if p10k is not None:
                svc["escale_eff"] = p10k.get("escale_eff")
            # Staged TPU-probe script (ROADMAP: the one-command live
            # window).  On a CPU-only box it still runs the staging
            # end to end and reports verdicts as pending-tpu.
            r = _run_stage("tpuprobe", label, {}, args.seconds,
                           600.0, force_cpu)
            if r is not None:
                svc["tpuprobe"] = {k2: v for k2, v in r.items()
                                   if k2 not in ("box", "platform")}
        # Flicker-window evidence (round 4): the preflight saw a live
        # accelerator but the headline landed on a CPU rung (or not at
        # all) — the chip is answering yet too slow/unstable for the
        # throughput loops.  Time single launches with a generous
        # budget; each completed launch is persisted, so even a
        # short alive-window produces a real-TPU datapoint.
        stepprobe = None
        if (probe is not None and probe.get("platform") != "cpu"
                and (svc is None or svc.get("platform") == "cpu")):
            stepprobe = _run_stepprobe(600.0, STEPPROBE_SHAPES)
        if svc is None:
            print(json.dumps({
                "metric": "service_linearizable_kv_ops_per_sec",
                "value": 0, "unit": "ops/sec", "vs_baseline": 0.0,
                "error": "every stage attempt timed out or crashed "
                         "(TPU backend unreachable?)",
                "tpu_stepprobe": stepprobe,
            }))
            sys.exit(1)
        if stepprobe is not None:
            svc["tpu_stepprobe"] = stepprobe

    baseline = 1_000_000.0  # north-star target (BASELINE.md)
    print(json.dumps({
        "metric": f"service_linearizable_kv_ops_per_sec_{label}",
        "value": round(svc["ops_per_sec"], 1),
        "unit": "ops/sec",
        "vs_baseline": round(svc["ops_per_sec"] / baseline, 3),
        "p50_commit_latency_ms": round(svc["p50_ms"], 3),
        "p99_commit_latency_ms": round(svc["p99_ms"], 3),
        "latency_batches": svc["batches"],
        # the headline loop's launch pipeline depth + the depth-1
        # serial reference (the silently-serialized-pipeline A/B)
        "pipeline_depth": svc.get("pipeline_depth"),
        "serial_ops_per_sec": (
            round(svc["serial_ops_per_sec"], 1)
            if svc.get("serial_ops_per_sec") else None),
        "serial_p50_ms": (round(svc["serial_p50_ms"], 3)
                          if svc.get("serial_p50_ms") else None),
        "serial_p99_ms": (round(svc["serial_p99_ms"], 3)
                          if svc.get("serial_p99_ms") else None),
        "serial_latency_breakdown_ms": svc.get(
            "serial_latency_breakdown"),
        "engine_kernel_rounds_per_sec": (
            round(svc["kernel_rounds_per_sec"], 1)
            if svc.get("kernel_rounds_per_sec") else None),
        "kernel_label": svc.get("kernel_label", label),
        "keyed_service_ops_per_sec": (
            round(svc["keyed_ops_per_sec"], 1)
            if svc.get("keyed_ops_per_sec") else None),
        "keyed_batched_ops_per_sec": (
            round(svc["keyed_batched_ops_per_sec"], 1)
            if svc.get("keyed_batched_ops_per_sec") else None),
        "mixed_ops_per_sec": (
            round(svc["mixed_ops_per_sec"], 1)
            if svc.get("mixed_ops_per_sec") else None),
        "mixed_p50_ms": (round(svc["mixed_p50_ms"], 3)
                         if svc.get("mixed_p50_ms") else None),
        "mixed_p99_ms": (round(svc["mixed_p99_ms"], 3)
                         if svc.get("mixed_p99_ms") else None),
        "mixed_commit_fraction": svc.get("mixed_commit_fraction"),
        # mixed-rung tail attribution: which latency mark dominated
        # each >5x-p50 batch (the formerly unexplained mixed_p99)
        "mixed_tail_batches": svc.get("mixed_tail_batches"),
        "mixed_tail_causes": svc.get("mixed_tail_causes"),
        "mixed_tail_top_cause": svc.get("mixed_tail_top_cause"),
        "rmw_device_ops_per_sec": (
            round(svc["rmw_device_ops_per_sec"], 1)
            if svc.get("rmw_device_ops_per_sec") else None),
        "rmw_host_ops_per_sec": (
            round(svc["rmw_host_ops_per_sec"], 1)
            if svc.get("rmw_host_ops_per_sec") else None),
        "rmw_device_speedup": (
            round(svc["rmw_device_speedup"], 2)
            if svc.get("rmw_device_speedup") else None),
        "rmw_device_flushes_per_round": svc.get(
            "rmw_device_flushes_per_round"),
        "rmw_host_flushes_per_round": svc.get(
            "rmw_host_flushes_per_round"),
        "skewed_service_ops_per_sec": (
            round(svc["skewed_ops_per_sec"], 1)
            if svc.get("skewed_ops_per_sec") else None),
        "skewed_baseline_ops_per_sec": (
            round(svc["skewed_baseline_ops_per_sec"], 1)
            if svc.get("skewed_baseline_ops_per_sec") else None),
        "skewed_compaction_speedup": svc.get(
            "skewed_compaction_speedup"),
        "payload_bytes_per_flush": svc.get("payload_bytes_per_flush"),
        "payload_bytes_full_width_per_flush": svc.get(
            "payload_bytes_full_width_per_flush"),
        "grid_occupancy": svc.get("grid_occupancy"),
        # lease-protected read fast path: the read-heavy rung with
        # its fastpath-off A/B arm
        "read_service_ops_per_sec": (
            round(svc["read_service_ops_per_sec"], 1)
            if svc.get("read_service_ops_per_sec") else None),
        "read_only_ops_per_sec": (
            round(svc["read_only_ops_per_sec"], 1)
            if svc.get("read_only_ops_per_sec") else None),
        "read_baseline_only_ops_per_sec": (
            round(svc["read_baseline_only_ops_per_sec"], 1)
            if svc.get("read_baseline_only_ops_per_sec") else None),
        "read_fastpath_speedup": svc.get("read_fastpath_speedup"),
        "read_hit_rate": svc.get("read_hit_rate"),
        "read_fastpath_hits": svc.get("read_fastpath_hits"),
        "read_fastpath_misses": svc.get("read_fastpath_misses"),
        "read_miss_reasons": svc.get("read_miss_reasons"),
        "read_p50_ms": svc.get("read_p50_ms"),
        "read_p99_ms": svc.get("read_p99_ms"),
        "repgroup_ops_per_sec": svc.get("repgroup_ops_per_sec"),
        "repgroup_p50_ms": svc.get("repgroup_p50_ms"),
        "repgroup_p99_ms": svc.get("repgroup_p99_ms"),
        "repgroup_baseline_ops_per_sec":
            svc.get("repgroup_baseline_ops_per_sec"),
        "repl_delta_speedup": svc.get("repl_delta_speedup"),
        "repl_bytes_per_entry": svc.get("repl_bytes_per_entry"),
        "repl_bytes_per_entry_full_plane":
            svc.get("repl_bytes_per_entry_full_plane"),
        "repl_ship_breakdown_ms": svc.get("repl_ship_breakdown_ms"),
        "latency_breakdown_ms": svc.get("latency_breakdown"),
        "tpu_stepprobe": svc.get("tpu_stepprobe"),
        # observability plane: the obs-on/off A/B (acceptance: on
        # within 3% of off on the same box) + flight-recorder
        # evidence for the mixed rung
        "obs_on_ops_per_sec": (
            round(svc["obs_on_ops_per_sec"], 1)
            if svc.get("obs_on_ops_per_sec") else None),
        "obs_off_ops_per_sec": (
            round(svc["obs_off_ops_per_sec"], 1)
            if svc.get("obs_off_ops_per_sec") else None),
        "obs_overhead_pct": svc.get("obs_overhead_pct"),
        # per-op SLO tracing A/B on the keyed rung (acceptance: on
        # within 2% of off — the ring stamps live on this path)
        "op_trace_on_ops_per_sec": (
            round(svc["op_trace_on_ops_per_sec"], 1)
            if svc.get("op_trace_on_ops_per_sec") else None),
        "op_trace_off_ops_per_sec": (
            round(svc["op_trace_off_ops_per_sec"], 1)
            if svc.get("op_trace_off_ops_per_sec") else None),
        "op_trace_overhead_pct": svc.get("op_trace_overhead_pct"),
        "mixed_flight_anomalies": svc.get("mixed_flight_anomalies"),
        # native single-pass resolve kernel: the interleaved on/off
        # A/B on the WAL'd keyed batched rung, plus the native arm's
        # component breakdown (where the batch time goes after the
        # kernel — the honest form of the 'bottleneck moved off
        # resolve' claim)
        "resolve_native_available": svc.get(
            "resolve_native_available"),
        "resolve_native_speedup": svc.get("resolve_native_speedup"),
        "resolve_native_ops_per_sec": (
            round(svc["resolve_native_ops_per_sec"], 1)
            if svc.get("resolve_native_ops_per_sec") else None),
        "resolve_fallback_ops_per_sec": (
            round(svc["resolve_fallback_ops_per_sec"], 1)
            if svc.get("resolve_fallback_ops_per_sec") else None),
        "resolve_native_latency_breakdown_ms": svc.get(
            "resolve_native_latency_breakdown"),
        # slab enqueue half (ARCHITECTURE §12): the interleaved
        # on/off A/B on the same WAL'd keyed rung, the acceptance
        # criterion's queue_wait+resolve p50 cut per arm, the on
        # arm's breakdown (with the derived enqueue_native/
        # enqueue_fallback pack marks), and the completion slab's
        # one-wake-per-flush ledger
        "enqueue_native_available": svc.get(
            "enqueue_native_available"),
        "enqueue_native_speedup": svc.get("enqueue_native_speedup"),
        "enqueue_native_ops_per_sec": (
            round(svc["enqueue_native_ops_per_sec"], 1)
            if svc.get("enqueue_native_ops_per_sec") else None),
        "enqueue_fallback_ops_per_sec": (
            round(svc["enqueue_fallback_ops_per_sec"], 1)
            if svc.get("enqueue_fallback_ops_per_sec") else None),
        "enqueue_queue_wait_resolve_p50_ms": svc.get(
            "enqueue_queue_wait_resolve_p50_ms"),
        "enqueue_native_latency_breakdown_ms": svc.get(
            "enqueue_native_latency_breakdown"),
        "enqueue_completion_slab": svc.get(
            "enqueue_completion_slab"),
        # adversarial fault-injection rungs (ARCHITECTURE §13): the
        # RTT sweep's depth-1/2 points, the fsync-delay rung and the
        # noisy-tenant isolation A/B, with the injected fault config
        # embedded next to the box fingerprint
        "faultsweep": svc.get("faultsweep"),
        "faultsweep_depth2_speedup": svc.get(
            "faultsweep_depth2_speedup"),
        # E-scaling CPU datapoints (1k always, 2k when the box
        # allows) — the curve alongside the 512-ens headline rung
        "escale_cpu": svc.get("escale_cpu"),
        # mesh E-scaling ladder (10k/32k/best-effort 100k on the
        # 8-device mesh) + the single-shard equal-per-shard-load
        # references; escale_eff is the >=10k rung's scaling
        # efficiency — the bench_trend ratchet column
        "escale_mesh": svc.get("escale_mesh"),
        "escale_eff": svc.get("escale_eff"),
        # staged TPU probe (--stage tpuprobe): compile ledger, ladder
        # and the Pallas-quorum/wide keep/kill verdicts (pending-tpu
        # until a live window executes them on a real accelerator)
        "tpuprobe": svc.get("tpuprobe"),
        # bench-trend ratchet (smoke path): the trajectory check's
        # report — rounds folded, newest headline, same-box band
        "bench_trend": svc.get("bench_trend"),
        **{k: round(v, 1) for k, v in svc.get("ladder", {}).items()},
        "platform": svc.get("platform", "unknown"),
        # the box this round's numbers were captured on — embedded so
        # cross-round deltas are checked against the box first
        "box": svc.get("box", _main_box()),
    }))


def _main_box():
    from riak_ensemble_tpu.obs import box_fingerprint
    return box_fingerprint()


if __name__ == "__main__":
    sys.exit(main())
