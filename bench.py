"""Headline benchmark: linearizable K/V throughput on the batched engine.

Scenario 3 of the BASELINE.md ladder: 10k ensembles x 5 peers driving
mixed kput/kget through the quorum-replicated data path (one election,
then steady-state leased operation).  The reference publishes no
numbers (BASELINE.md); the driver north-star target is >= 1M
linearizable ops/sec on TPU, which is the ``vs_baseline`` denominator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/sec", "vs_baseline": N}

``--smoke`` shrinks shapes for a CPU sanity run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def run(n_ens: int, n_peers: int, n_slots: int, k: int,
        seconds: float) -> float:
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    state = eng.init_state(n_ens, n_peers, n_slots)
    up = jnp.ones((n_ens, n_peers), bool)
    state, won = eng.elect_step(
        state, jnp.ones((n_ens,), bool), jnp.zeros((n_ens,), jnp.int32), up)

    rng = np.random.default_rng(0)
    kind = jnp.asarray(rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)),
                       jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (k, n_ens)), jnp.int32)
    val = jnp.asarray(rng.integers(1, 1 << 20, (k, n_ens)), jnp.int32)
    lease_ok = jnp.ones((k, n_ens), bool)

    # Compile + warm up.  NOTE: no device→host transfers before or
    # inside the timed region — on the tunneled single-chip platform a
    # d2h copy permanently degrades subsequent dispatches to a ~2 ms
    # synchronous path (measured 40x); correctness checks run AFTER
    # the timed loop instead.
    state2, _res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state2)

    # Calibrate per-step time (blocked, so it includes sync overhead —
    # a conservative estimate) to bound the enqueue depth: async
    # dispatch outruns the device by orders of magnitude, and an
    # unbounded wall-clock enqueue loop would queue minutes of drain.
    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        state, res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
        jax.block_until_ready(state)
    step_est = (time.perf_counter() - t0) / ncal

    # Timed loop: a bounded number of chained steps; ops advance real
    # protocol state.  The final block waits for every queued step, so
    # `elapsed` covers full execution, not just enqueue.
    iters = max(10, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    # Post-loop correctness: elections all won; every op in the last
    # step acked (puts committed / gets served or lease-bypassed).
    assert bool(np.asarray(won).all()), "bench: elections failed"
    ok = np.asarray(res.committed | res.get_ok | (np.asarray(kind) == 0))
    assert ok.all(), "bench: ops failed"
    return n_ens * k * iters / elapsed


def run_merkle(seconds: float, smoke: bool) -> dict:
    """BASELINE ladder #4: incremental updates into a 1M-segment
    Merkle tree (the always-up-to-date write-path hashing)."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import hash as hashk

    segs = 16 ** 3 if smoke else 16 ** 5
    batch = 256 if smoke else 4096
    rng = np.random.default_rng(0)
    leaves = jnp.zeros((segs, hashk.LANES), jnp.uint32)
    levels = hashk.build(leaves, width=16)
    ids = jnp.asarray(rng.integers(0, segs, batch))
    new = jnp.asarray(rng.integers(0, 2 ** 32, (batch, hashk.LANES),
                                   dtype=np.uint32))
    levels = hashk.update(levels, ids, new, width=16)
    jax.block_until_ready(levels)

    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        levels = hashk.update(levels, ids, new, width=16)
        jax.block_until_ready(levels)
    step_est = (time.perf_counter() - t0) / ncal
    iters = max(10, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        levels = hashk.update(levels, ids, new, width=16)
    jax.block_until_ready(levels)
    elapsed = time.perf_counter() - t0
    rate = batch * iters / elapsed
    return {
        "metric": f"merkle_key_updates_per_sec_{segs}_segments",
        "value": round(rate, 1),
        "unit": "updates/sec",
        "vs_baseline": round(rate / 1_000_000.0, 3),
    }


def run_reconfig(seconds: float, smoke: bool) -> dict:
    """BASELINE ladder #5: joint-consensus reconfig cycles under churn
    (install joint views + collapse), batched over all ensembles."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    n_ens, m = (64, 5) if smoke else (10_000, 5)
    state = eng.init_state(n_ens, m, 8)
    up = jnp.ones((n_ens, m), bool)
    state, won = eng.elect_step(state, jnp.ones((n_ens,), bool),
                                jnp.zeros((n_ens,), jnp.int32), up)
    rng = np.random.default_rng(0)
    keep = np.ones((n_ens, m), bool)
    keep[np.arange(n_ens), rng.integers(0, m, n_ens)] = False
    shrink = jnp.asarray(keep)
    full = jnp.ones((n_ens, m), bool)
    yes = jnp.ones((n_ens,), bool)
    no = jnp.zeros((n_ens,), bool)

    def cycle(st):
        st, _, _ = eng.reconfig_step(st, yes, shrink, up)
        st, _, _ = eng.reconfig_step(st, no, shrink, up)
        st, _, _ = eng.reconfig_step(st, yes, full, up)
        st, _, _ = eng.reconfig_step(st, no, full, up)
        return st

    state = cycle(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        state = cycle(state)
        jax.block_until_ready(state)
    step_est = (time.perf_counter() - t0) / ncal
    iters = max(5, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = cycle(state)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0
    assert bool(np.asarray(won).all())
    # 2 full membership changes (4 reconfig phases) per cycle per ens
    rate = 2 * n_ens * iters / elapsed
    return {
        "metric": f"membership_changes_per_sec_{n_ens}_ens",
        "value": round(rate, 1),
        "unit": "changes/sec",
        "vs_baseline": round(rate / 1_000_000.0, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a CPU sanity run")
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--scenario", default="kv",
                    choices=("kv", "merkle", "reconfig"),
                    help="kv = headline (driver default); merkle / "
                         "reconfig = BASELINE.md ladder #4 / #5")
    args = ap.parse_args()

    if args.scenario == "merkle":
        print(json.dumps(run_merkle(args.seconds, args.smoke)))
        return
    if args.scenario == "reconfig":
        print(json.dumps(run_reconfig(args.seconds, args.smoke)))
        return

    if args.smoke:
        ops_per_sec = run(n_ens=64, n_peers=5, n_slots=32, k=4,
                          seconds=min(args.seconds, 1.0))
    else:
        ops_per_sec = run(n_ens=10_000, n_peers=5, n_slots=128, k=64,
                          seconds=args.seconds)

    baseline = 1_000_000.0  # north-star target (BASELINE.md)
    print(json.dumps({
        "metric": "linearizable_kv_ops_per_sec_10k_ens_5_peers",
        "value": round(ops_per_sec, 1),
        "unit": "ops/sec",
        "vs_baseline": round(ops_per_sec / baseline, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
