"""Headline benchmark: the END-TO-END service, plus the raw kernel.

Scenario 3 of the BASELINE.md ladder: 10k ensembles x 5 peers of mixed
kput/kget.  Two numbers, measured in this order (a d2h transfer
permanently degrades dispatch on the tunneled chip, so the no-d2h
kernel loop runs first):

1. ``engine_kernel_rounds_per_sec`` — raw ``kv_step_scan`` launches,
   device math only (ballots, quorum reduce, store, Merkle maintenance;
   no host bridge).  An honest kernel number, not a service claim.
2. ``service_linearizable_kv_ops_per_sec`` — the HEADLINE:
   ``BatchedEnsembleService.execute`` end to end (election fold-in,
   host lease check/renewal, device launch, result transfer, corruption
   watch), with client-observed per-batch commit latency recorded —
   p50/p99 reported against the BASELINE.md targets (>= 1M ops/s,
   p99 < 5 ms).

The reference publishes no numbers (BASELINE.md); the driver north-star
target of 1M linearizable ops/sec is the ``vs_baseline`` denominator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/sec", "vs_baseline": N,
   "p50_commit_latency_ms": ..., "p99_commit_latency_ms": ...,
   "engine_kernel_rounds_per_sec": ...}

``--smoke`` shrinks shapes for a CPU sanity run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def run_service(n_ens: int, n_peers: int, n_slots: int, k: int,
                seconds: float) -> dict:
    """End-to-end service throughput + client-observed commit latency.

    Closed loop: each iteration submits a [K, E] batch of mixed
    put/get through ``BatchedEnsembleService.execute`` and blocks on
    the results (the resolve step every queued client future would
    ride).  Per-batch wall time IS each op's commit latency: ops
    enqueue at batch start and resolve when the batch returns.
    """
    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime,
    )

    svc = BatchedEnsembleService(WallRuntime(), n_ens, n_peers, n_slots,
                                 tick=None, max_ops_per_tick=k)
    rng = np.random.default_rng(0)
    kind = rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)).astype(np.int32)
    slot = rng.integers(0, n_slots, (k, n_ens)).astype(np.int32)
    val = rng.integers(1, 1 << 20, (k, n_ens)).astype(np.int32)

    # Warm up: compile + first elections fold into the launch.
    svc.execute(kind, slot, val)
    svc.execute(kind, slot, val)

    lat = []
    ops = 0
    t_end = time.perf_counter() + seconds
    t_start = time.perf_counter()
    while time.perf_counter() < t_end:
        t0 = time.perf_counter()
        committed, get_ok, found, value = svc.execute(kind, slot, val)
        lat.append(time.perf_counter() - t0)
        ops += k * n_ens
    elapsed = time.perf_counter() - t_start

    # Correctness on the final batch: every op acked.
    ok = committed | get_ok
    assert ok.all(), "service bench: ops failed"
    assert (np.asarray(svc.state.leader) >= 0).all()
    lat_ms = np.asarray(lat) * 1000.0
    return {
        "ops_per_sec": ops / elapsed,
        "p50_ms": float(np.percentile(lat_ms, 50)),
        "p99_ms": float(np.percentile(lat_ms, 99)),
        "batches": len(lat),
    }


def run(n_ens: int, n_peers: int, n_slots: int, k: int,
        seconds: float) -> float:
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    state = eng.init_state(n_ens, n_peers, n_slots)
    up = jnp.ones((n_ens, n_peers), bool)
    state, won = eng.elect_step(
        state, jnp.ones((n_ens,), bool), jnp.zeros((n_ens,), jnp.int32), up)

    rng = np.random.default_rng(0)
    kind = jnp.asarray(rng.choice([eng.OP_PUT, eng.OP_GET], (k, n_ens)),
                       jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (k, n_ens)), jnp.int32)
    val = jnp.asarray(rng.integers(1, 1 << 20, (k, n_ens)), jnp.int32)
    lease_ok = jnp.ones((k, n_ens), bool)

    # Compile + warm up.  NOTE: no device→host transfers before or
    # inside the timed region — on the tunneled single-chip platform a
    # d2h copy permanently degrades subsequent dispatches to a ~2 ms
    # synchronous path (measured 40x); correctness checks run AFTER
    # the timed loop instead.
    state2, _res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state2)

    # Calibrate per-step time (blocked, so it includes sync overhead —
    # a conservative estimate) to bound the enqueue depth: async
    # dispatch outruns the device by orders of magnitude, and an
    # unbounded wall-clock enqueue loop would queue minutes of drain.
    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        state, res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
        jax.block_until_ready(state)
    step_est = (time.perf_counter() - t0) / ncal

    # Timed loop: a bounded number of chained steps; ops advance real
    # protocol state.  The final block waits for every queued step, so
    # `elapsed` covers full execution, not just enqueue.
    iters = max(10, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, res = eng.kv_step_scan(state, kind, slot, val, lease_ok, up)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    # Post-loop correctness: elections all won; every op in the last
    # step acked (puts committed / gets served or lease-bypassed).
    assert bool(np.asarray(won).all()), "bench: elections failed"
    ok = np.asarray(res.committed | res.get_ok | (np.asarray(kind) == 0))
    assert ok.all(), "bench: ops failed"
    return n_ens * k * iters / elapsed


def run_merkle(seconds: float, smoke: bool) -> dict:
    """BASELINE ladder #4: incremental updates into a 1M-segment
    Merkle tree (the always-up-to-date write-path hashing)."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import hash as hashk

    segs = 16 ** 3 if smoke else 16 ** 5
    batch = 256 if smoke else 4096
    rng = np.random.default_rng(0)
    leaves = jnp.zeros((segs, hashk.LANES), jnp.uint32)
    levels = hashk.build(leaves, width=16)
    ids = jnp.asarray(rng.integers(0, segs, batch))
    new = jnp.asarray(rng.integers(0, 2 ** 32, (batch, hashk.LANES),
                                   dtype=np.uint32))
    levels = hashk.update(levels, ids, new, width=16)
    jax.block_until_ready(levels)

    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        levels = hashk.update(levels, ids, new, width=16)
        jax.block_until_ready(levels)
    step_est = (time.perf_counter() - t0) / ncal
    iters = max(10, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        levels = hashk.update(levels, ids, new, width=16)
    jax.block_until_ready(levels)
    elapsed = time.perf_counter() - t0
    rate = batch * iters / elapsed
    return {
        "metric": f"merkle_key_updates_per_sec_{segs}_segments",
        "value": round(rate, 1),
        "unit": "updates/sec",
        "vs_baseline": round(rate / 1_000_000.0, 3),
    }


def run_reconfig(seconds: float, smoke: bool) -> dict:
    """BASELINE ladder #5: joint-consensus reconfig cycles under churn
    (install joint views + collapse), batched over all ensembles."""
    import jax
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    n_ens, m = (64, 5) if smoke else (10_000, 5)
    state = eng.init_state(n_ens, m, 8)
    up = jnp.ones((n_ens, m), bool)
    state, won = eng.elect_step(state, jnp.ones((n_ens,), bool),
                                jnp.zeros((n_ens,), jnp.int32), up)
    rng = np.random.default_rng(0)
    keep = np.ones((n_ens, m), bool)
    keep[np.arange(n_ens), rng.integers(0, m, n_ens)] = False
    shrink = jnp.asarray(keep)
    full = jnp.ones((n_ens, m), bool)
    yes = jnp.ones((n_ens,), bool)
    no = jnp.zeros((n_ens,), bool)

    def cycle(st):
        st, _, _ = eng.reconfig_step(st, yes, shrink, up)
        st, _, _ = eng.reconfig_step(st, no, shrink, up)
        st, _, _ = eng.reconfig_step(st, yes, full, up)
        st, _, _ = eng.reconfig_step(st, no, full, up)
        return st

    state = cycle(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    ncal = 3
    for _ in range(ncal):
        state = cycle(state)
        jax.block_until_ready(state)
    step_est = (time.perf_counter() - t0) / ncal
    iters = max(5, int(seconds / step_est))
    t0 = time.perf_counter()
    for _ in range(iters):
        state = cycle(state)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0
    assert bool(np.asarray(won).all())
    # 2 full membership changes (4 reconfig phases) per cycle per ens
    rate = 2 * n_ens * iters / elapsed
    return {
        "metric": f"membership_changes_per_sec_{n_ens}_ens",
        "value": round(rate, 1),
        "unit": "changes/sec",
        "vs_baseline": round(rate / 1_000_000.0, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a CPU sanity run")
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--scenario", default="kv",
                    choices=("kv", "merkle", "reconfig"),
                    help="kv = headline (driver default); merkle / "
                         "reconfig = BASELINE.md ladder #4 / #5")
    args = ap.parse_args()

    if args.scenario == "merkle":
        print(json.dumps(run_merkle(args.seconds, args.smoke)))
        return
    if args.scenario == "reconfig":
        print(json.dumps(run_reconfig(args.seconds, args.smoke)))
        return

    if args.smoke:
        shapes = dict(n_ens=64, n_peers=5, n_slots=32, k=4)
        secs = min(args.seconds, 1.0)
    else:
        shapes = dict(n_ens=10_000, n_peers=5, n_slots=128, k=64)
        secs = args.seconds
    # Kernel first: it must run before any d2h (see module docstring).
    kernel_rounds = run(seconds=secs, **shapes)
    svc = run_service(seconds=secs, **shapes)

    baseline = 1_000_000.0  # north-star target (BASELINE.md)
    print(json.dumps({
        "metric": "service_linearizable_kv_ops_per_sec_10k_ens_5_peers",
        "value": round(svc["ops_per_sec"], 1),
        "unit": "ops/sec",
        "vs_baseline": round(svc["ops_per_sec"] / baseline, 3),
        "p50_commit_latency_ms": round(svc["p50_ms"], 3),
        "p99_commit_latency_ms": round(svc["p99_ms"], 3),
        "latency_batches": svc["batches"],
        "engine_kernel_rounds_per_sec": round(kernel_rounds, 1),
    }))


if __name__ == "__main__":
    sys.exit(main())
