"""Opportunistic TPU measurement harness (VERDICT r2 directive #1).

The tunneled TPU backend has died mid-session in both prior rounds,
so waiting until round-end bench time risks closing another round
with zero TPU evidence.  This script is run repeatedly through the
session: each invocation probes the accelerator with a tiny compiled
op under a hard timeout; if (and only if) the chip answers, it runs
the full bench ladder — service ops/s + p50/p99, kernel rounds/s,
Pallas quorum A/B, Merkle + reconfig ladder — and PERSISTS the result
immediately (``BENCH_TPU_attempt.json``) so a later tunnel death
cannot erase it.  Every attempt (dead or alive) appends to
``.attempts/tpu_probe_log.txt``.

Exit code: 0 = measured and persisted, 2 = chip dead, 3 = probe ok
but a later stage failed (partial results persisted).
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(HERE, ".attempts", "tpu_probe_log.txt")
OUT = os.path.join(HERE, "BENCH_TPU_attempt.json")


def note(msg: str) -> None:
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")
    with open(LOG, "a") as f:
        f.write(f"{stamp} {msg}\n")
    print(f"{stamp} {msg}", flush=True)


def run_stage(args, timeout):
    """One bench stage in a killable subprocess (a wedged TPU RPC
    ignores signals; only a process-group kill unsticks it)."""
    import signal

    cmd = [sys.executable, os.path.join(HERE, "bench.py")] + args
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            pass
        return None, "timeout"
    if proc.returncode != 0:
        return None, f"rc={proc.returncode} {err[-300:]}"
    for line in reversed(out.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no json"


def main() -> int:
    probe_budget = float(os.environ.get("TPU_PROBE_BUDGET", "300"))
    res, err = run_stage(["--stage", "probe"], probe_budget)
    if res is None or res.get("platform") == "cpu":
        note(f"probe dead ({err or 'cpu fallback'})")
        return 2
    note(f"probe ALIVE platform={res['platform']} — running full ladder")

    results = {"platform": res["platform"],
               "probe_time": datetime.datetime.now(
                   datetime.timezone.utc).isoformat()}

    def persist() -> None:
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    # Stage order mirrors bench.py: kernel FIRST (d2h degrades later
    # dispatch on the tunneled chip), then service, ladder, A/B.
    shapes = ["--n-ens", "10000", "--n-peers", "5", "--n-slots", "128",
              "--k", "64"]
    stages = [
        ("kernel", ["--stage", "kernel", "--seconds", "3"] + shapes, 480),
        ("service", ["--stage", "service", "--seconds", "3"] + shapes, 480),
        ("merkle", ["--stage", "merkle", "--seconds", "3"], 420),
        ("reconfig", ["--stage", "reconfig", "--seconds", "3"], 420),
    ]
    ok = True
    for name, args, budget in stages:
        r, err = run_stage(args, budget)
        if r is None:
            note(f"stage {name} FAILED ({err})")
            results[name] = {"error": err}
            ok = False
            # Fall back to the 1k shape once for the big stages.
            if name in ("kernel", "service"):
                small = ["--n-ens", "1000", "--n-peers", "5",
                         "--n-slots", "128", "--k", "32"]
                r2, err2 = run_stage(
                    ["--stage", name, "--seconds", "3"] + small, 360)
                if r2 is not None:
                    results[name] = {"shape": "1k_ens_5_peers", **r2}
                    note(f"stage {name} ok at 1k fallback")
        else:
            results[name] = r
            note(f"stage {name} ok: {json.dumps(r)[:200]}")
        persist()

    # Pallas quorum A/B: the same kernel stage with the Pallas reduce
    # flag — the delta promised since round 1.
    env = dict(os.environ, RETPU_PALLAS_QUORUM="1")
    cmd = [sys.executable, os.path.join(HERE, "bench.py"), "--stage",
           "kernel", "--seconds", "3"] + shapes
    import signal
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            env=env, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=480)
        for line in reversed(out.strip().splitlines()):
            try:
                results["kernel_pallas_quorum"] = json.loads(line)
                note("pallas A/B ok: "
                     + json.dumps(results['kernel_pallas_quorum'])[:200])
                break
            except json.JSONDecodeError:
                continue
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        note("pallas A/B timeout")
        results["kernel_pallas_quorum"] = {"error": "timeout"}
        ok = False
    persist()
    note(f"ladder complete ok={ok} -> {OUT}")
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
