"""Opportunistic TPU measurement harness (VERDICT r2 directive #1).

The tunneled TPU backend has died mid-session in both prior rounds,
so waiting until round-end bench time risks closing another round
with zero TPU evidence.  This script is run repeatedly through the
session: each invocation probes the accelerator with a tiny compiled
op under a hard timeout; if (and only if) the chip answers, it runs
the full bench ladder — service ops/s + p50/p99, kernel rounds/s,
Pallas quorum A/B, Merkle + reconfig ladder — and PERSISTS the result
immediately (``BENCH_TPU_attempt.json``) so a later tunnel death
cannot erase it.  Every attempt (dead or alive) appends to
``.attempts/tpu_probe_log.txt``.

Exit code: 0 = measured and persisted, 2 = chip dead, 3 = probe ok
but a later stage failed (partial results persisted).
"""

from __future__ import annotations

import datetime
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(HERE, ".attempts", "tpu_probe_log.txt")
OUT = os.path.join(HERE, "BENCH_TPU_attempt.json")

sys.path.insert(0, HERE)
import bench as _bench  # noqa: E402  (light import; no JAX init)


def note(msg: str) -> None:
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")
    with open(LOG, "a") as f:
        f.write(f"{stamp} {msg}\n")
    print(f"{stamp} {msg}", flush=True)


def run_stage(args, timeout, env=None):
    """One bench stage in a killable subprocess (a wedged TPU RPC
    ignores signals; only a process-group kill unsticks it).
    Delegates the spawn/kill/parse lifecycle to bench._spawn_stage so
    the two harnesses cannot diverge."""
    cmd = [sys.executable, os.path.join(HERE, "bench.py")] + args
    return _bench._spawn_stage(cmd, timeout, env=env)


def main() -> int:
    probe_budget = float(os.environ.get("TPU_PROBE_BUDGET", "300"))
    res, err = run_stage(["--stage", "probe"], probe_budget)
    if res is None or res.get("platform") == "cpu":
        note(f"probe dead ({err or 'cpu fallback'})")
        return 2
    note(f"probe ALIVE platform={res['platform']} — running full ladder")

    results = {"platform": res["platform"],
               "probe_time": datetime.datetime.now(
                   datetime.timezone.utc).isoformat()}

    def persist() -> None:
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    # STEPPROBE FIRST (round-4 lesson): the 03:17Z alive-window
    # compiled every stage kernel but executed launches too slowly
    # for any throughput stage to finish inside its budget, and the
    # tunnel died again ~50 min later with zero numbers banked.
    # Single-launch timings persist incrementally, so even a brief
    # window yields an honest ops/s figure — and the measured step
    # latency then sizes the real ladder's budgets (or tells us to
    # keep the small shapes first).
    sp = _bench._run_stepprobe(900.0, _bench.STEPPROBE_SHAPES)
    results["stepprobe"] = sp
    if sp is not None and sp.get("cpu_fallback"):
        # The tunnel died between the preflight and here; a CPU step
        # time would size TPU budgets wrong AND masquerade as TPU
        # evidence.
        note("stepprobe landed on cpu — accelerator gone; aborting ladder")
        persist()
        return 3
    persist()
    step_s = (sp or {}).get("median_step_s")
    note(f"stepprobe: {json.dumps(sp)[:200] if sp else 'no launch completed'}")

    completed_any = sp is not None and (
        sp.get("steps_s") or "first_step_s" in sp)
    if not completed_any:
        # The chip could not finish ONE launch in 900 s.  Running the
        # full ladder (~7 more stages of near-guaranteed timeouts)
        # would burn ~an hour of probe cadence against a backend that
        # failed the cheapest possible operation — bail and let the
        # next probe cycle try again.
        note("stepprobe completed zero launches — skipping ladder")
        persist()
        return 3

    # Budgets adapt to the measured launch latency: each throughput
    # stage needs ~15 sequential launches beyond compile (warmup +
    # 3-step calibration + >=10-iteration loop).
    slow = step_s is None or step_s > 5.0
    pad = 300.0 + (20.0 * step_s if step_s else 0.0)
    big = max(480.0, min(1800.0, pad))

    # Stage order mirrors bench.py: kernel FIRST (d2h degrades later
    # dispatch on the tunneled chip), then service, ladder, A/B.
    # On a slow chip the 1k shape runs FIRST so a short alive-window
    # banks the small number before the big shape gambles the rest.
    shapes = ["--n-ens", "10000", "--n-peers", "5", "--n-slots", "128",
              "--k", "64"]
    small = ["--n-ens", "1000", "--n-peers", "5", "--n-slots", "128",
             "--k", "32"]
    if slow:
        stages = [
            ("kernel_1k", ["--stage", "kernel", "--seconds", "3"] + small,
             big),
            ("service_1k", ["--stage", "service", "--seconds", "3"] + small,
             big),
            ("kernel", ["--stage", "kernel", "--seconds", "3"] + shapes,
             big),
            ("service", ["--stage", "service", "--seconds", "3"] + shapes,
             big),
            ("merkle", ["--stage", "merkle", "--seconds", "3"], 420),
            ("reconfig", ["--stage", "reconfig", "--seconds", "3"], 420),
        ]
    else:
        stages = [
            ("kernel", ["--stage", "kernel", "--seconds", "3"] + shapes,
             big),
            ("service", ["--stage", "service", "--seconds", "3"] + shapes,
             big),
            ("merkle", ["--stage", "merkle", "--seconds", "3"], 420),
            ("reconfig", ["--stage", "reconfig", "--seconds", "3"], 420),
        ]
    ok = True
    for name, args, budget in stages:
        r, err = run_stage(args, budget)
        if r is None:
            note(f"stage {name} FAILED ({err})")
            results[name] = {"error": err}
            ok = False
            # Fall back to the 1k shape once for the big stages
            # (unless the slow ladder already ran the 1k rung first).
            if name in ("kernel", "service") and not slow:
                r2, err2 = run_stage(
                    ["--stage", name, "--seconds", "3"] + small, 360)
                if r2 is not None:
                    results[name] = {"shape": "1k_ens_5_peers", **r2}
                    note(f"stage {name} ok at 1k fallback")
        else:
            results[name] = r
            note(f"stage {name} ok: {json.dumps(r)[:200]}")
        persist()

    def run_ab(name: str, stage: str, baseline_key: str,
               env=None) -> bool:
        """One A/B arm: an A/B delta is a ratio, so it must run at the
        SAME shape as the baseline number that actually banked — 10k
        only if the baseline stage succeeded at 10k, else the 1k shape
        (re-running a shape that already timed out is a guaranteed
        re-timeout)."""
        base = results.get(baseline_key) or {}
        at_10k = "error" not in base and base.get("shape") is None
        ab_shapes = shapes if at_10k else small
        r, err = run_stage(
            ["--stage", stage, "--seconds", "3"] + ab_shapes, big,
            env=env)
        if r is not None:
            if not at_10k:
                r = {"shape": "1k_ens_5_peers", **r}
            results[name] = r
            note(f"{name} A/B ok: {json.dumps(r)[:200]}")
            return True
        note(f"{name} A/B FAILED ({err})")
        results[name] = {"error": err}
        return False

    # Pallas quorum A/B (the delta promised since round 1) and the
    # wide-scheduling A/B (round 4: CPU-neutral, built for exactly
    # this platform's launch-overhead profile — widecmp runs the SAME
    # distinct-slot plane through both arms in one process, since a
    # random-slot plane would chain past the wide gate and silently
    # compare scalar against scalar).
    ok &= run_ab("kernel_pallas_quorum", "kernel", "kernel",
                 env=dict(os.environ, RETPU_PALLAS_QUORUM="1"))
    persist()
    ok &= run_ab("service_widecmp", "widecmp", "service")
    persist()
    note(f"ladder complete ok={ok} -> {OUT}")
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
